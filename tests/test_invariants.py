"""Randomized property tests: every solver must uphold the structural
invariants (validity, rack exclusivity, capacity, stickiness) on generated
clusters scaled down from the BASELINE configs — the test style SURVEY.md §4
prescribes in place of the reference's four fixed scenarios."""
from __future__ import annotations

import math
import random

import pytest

from kafka_assigner_tpu.assigner import TopicAssigner

from .helpers import moved_replicas, verify_full_invariants
from .test_strategy_scenarios import SOLVERS


def make_cluster(seed, n_brokers, n_partitions, rf, n_racks, remove=0, add=0):
    """Build (current_assignment, live_brokers, racks): a balanced, rack-valid
    current assignment (as Kafka's own round-robin assigner would produce,
    generated here by a fresh greedy solve), then a membership change.
    Removals are spread across racks — the reference's greedy is documented to
    dead-end on rack-unbalanced clusters (KafkaAssignmentStrategy.java:29-30),
    so tests stay within its supported envelope."""
    rng = random.Random(seed)
    base = list(range(100, 100 + n_brokers))
    racks = {b: f"rack{i % n_racks}" for i, b in enumerate(base)}
    # Balanced rack-aware start via rack-interleaved striping: order brokers
    # rack0[0], rack1[0], ..., rackR[0], rack0[1], ...; partition p takes rf
    # consecutive entries starting at p. Consecutive entries sit on distinct
    # racks, and every broker carries ~P*rf/N replicas — the shape Kafka's own
    # assigner produces.
    by_rack = {}
    for b in base:
        by_rack.setdefault(racks[b], []).append(b)
    depth = max(len(v) for v in by_rack.values())
    interleaved = [
        by_rack[r][d]
        for d in range(depth)
        for r in sorted(by_rack)
        if d < len(by_rack[r])
    ]
    n = len(interleaved)
    current = {
        p: [interleaved[(p + i) % n] for i in range(rf)] for p in range(n_partitions)
    }
    live = list(base)
    if remove:
        by_rack = {}
        for b in rng.sample(base, len(base)):
            by_rack.setdefault(racks[b], []).append(b)
        removed = set()
        rack_cycle = sorted(by_rack)
        i = 0
        while len(removed) < remove:
            bucket = by_rack[rack_cycle[i % len(rack_cycle)]]
            if bucket:
                removed.add(bucket.pop())
            i += 1
        live = [b for b in live if b not in removed]
    for j in range(add):
        nb = 100 + n_brokers + j
        live.append(nb)
        racks[nb] = f"rack{(n_brokers + j) % n_racks}"
    rack_map = {b: racks[b] for b in live}
    return current, set(live), rack_map


# (brokers, partitions, rf, racks, remove, add) — shrunk BASELINE configs 1-3,
# all within the greedy's practical envelope (cluster-scale broker counts, low
# per-node caps; the reference's first-fit is documented to dead-end outside it,
# KafkaAssignmentStrategy.java:29-30).
CASES = [
    (10, 50, 3, 5, 0, 0),   # steady state, fully saturated caps
    (12, 40, 3, 3, 3, 0),   # decommission one broker per rack
    (30, 40, 3, 5, 5, 0),   # decommission at cluster scale
    (20, 30, 3, 5, 0, 5),   # rack-aware expansion
    (25, 30, 3, 5, 5, 5),   # replacement (remove 5, add 5)
    (10, 40, 2, 5, 0, 5),   # rf=2 expansion
    (12, 40, 2, 4, 2, 2),   # rf=2 replacement
    (15, 60, 1, 5, 3, 0),   # rf=1 decommission
    (24, 64, 3, 4, 4, 0),   # 4 racks, one removal per rack
]


@pytest.mark.parametrize("solver", SOLVERS)
@pytest.mark.parametrize("case", CASES, ids=[str(c) for c in CASES])
def test_random_cluster_invariants(solver, case):
    n_brokers, n_partitions, rf, n_racks, remove, add = case
    for seed in range(3):
        current, live, rack_map = make_cluster(
            seed, n_brokers, n_partitions, rf, n_racks, remove, add
        )
        assigner = TopicAssigner(solver)
        new = assigner.generate_assignment("topic-%d" % seed, current, live, rack_map, -1)
        assert set(new) == set(current)
        verify_full_invariants(new, rack_map, sorted(live), rf)
        # Aggregate stickiness: movement is bounded by replicas that *had* to
        # move — dead brokers ("lost") plus capacity evictions when the
        # per-node cap tightens ("forced", e.g. on expansion a fraction of each
        # broker's replicas must migrate to the new brokers,
        # KafkaTopicAssigner.java:28-31) — plus small churn slack.
        # (Per-partition retention is NOT an invariant of the reference: under
        # capacity pressure the sticky fill can evict a partition's last
        # survivor, KafkaAssignmentStrategy.java:120-124.)
        total = len(current) * rf
        cap = math.ceil(total / len(live))
        lost = sum(1 for r in current.values() for b in r if b not in live)
        load = {}
        for r in current.values():
            for b in r:
                load[b] = load.get(b, 0) + 1
        forced = sum(max(0, c - cap) for b, c in load.items() if b in live)
        moved = moved_replicas(current, new)
        assert moved <= lost + forced + 0.15 * total, (
            f"moved={moved} lost={lost} forced={forced} total={total}: excessive churn"
        )


@pytest.mark.parametrize("solver", SOLVERS)
def test_no_change_is_noop_movement(solver):
    # Rebalancing an already-balanced cluster must move (almost) nothing.
    current, live, rack_map = make_cluster(7, 12, 48, 3, 4)
    assigner = TopicAssigner(solver)
    new = assigner.generate_assignment("steady", current, live, rack_map, -1)
    moved = moved_replicas(current, new)
    # capacity = ceil(48*3/12) = 12; a balanced-ish random start may exceed the
    # cap on a few nodes, so allow a small shuffle but not churn.
    assert moved <= 48 * 3 * 0.25, f"moved {moved} replicas on a no-op rebalance"


@pytest.mark.parametrize("solver", SOLVERS)
def test_decommission_moves_only_lost_replicas(solver):
    current, live, rack_map = make_cluster(3, 30, 40, 3, 5, remove=5)
    assigner = TopicAssigner(solver)
    new = assigner.generate_assignment("decom", current, live, rack_map, -1)
    lost = sum(1 for r in current.values() for b in r if b not in live)
    moved = moved_replicas(current, new)
    # Movement should be dominated by the replicas that *had* to move, with
    # limited extra churn from capacity tightening.
    assert moved <= lost + 40 * 3 * 0.1, f"moved={moved} lost={lost}"
