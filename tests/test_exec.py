"""The plan execution engine (ISSUE 7): journal lifecycle and crash
safety, wave split + throttled convergence against the snapshot backend's
simulated cluster, the write-safety read-back rule, the documented
``ka-execute`` exit codes (ok / resume / degraded / verify-mismatch), and
the degraded-run diff surfaced in the run report's plan section."""
from __future__ import annotations

import contextlib
import io
import json
import os
import shutil

import pytest

from kafka_assigner_tpu import faults
from kafka_assigner_tpu.cli import (
    EXIT_DEGRADED,
    EXIT_EXECUTE,
    EXIT_OK,
    EXIT_VALIDATION,
    EXIT_VERIFY,
    execute,
    run,
)
from kafka_assigner_tpu.exec.engine import (
    PlanExecutor,
    load_plan_file,
)
from kafka_assigner_tpu.exec.journal import (
    ExecutionJournal,
    JournalError,
    plan_fingerprint,
)
from kafka_assigner_tpu.faults.inject import InjectedExecCrash
from kafka_assigner_tpu.io.snapshot import SnapshotBackend


@pytest.fixture(autouse=True)
def _fresh_injector():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(autouse=True)
def _fast_exec_env(monkeypatch):
    """Tight wave/poll knobs so every test runs in milliseconds; the sim
    convergence needs one extra poll per move (KA_EXEC_SIM_POLLS=1), which
    keeps the retry path honest."""
    monkeypatch.setenv("KA_EXEC_WAVE_SIZE", "3")
    monkeypatch.setenv("KA_EXEC_POLL_INTERVAL", "0.01")
    monkeypatch.setenv("KA_EXEC_POLL_TIMEOUT", "10")
    monkeypatch.setenv("KA_EXEC_SIM_POLLS", "1")


def _cluster():
    from .jute_server import exec_snapshot_cluster

    return exec_snapshot_cluster()


@pytest.fixture(scope="module")
def plan_text(tmp_path_factory):
    """One real multi-wave plan (greedy mode 3, broker h9 drained), built
    once for the module: the full mode-3 stdout, banners included — what an
    operator actually saves."""
    d = tmp_path_factory.mktemp("exec_plan")
    src = d / "cluster.json"
    src.write_text(json.dumps(_cluster()))
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = run([
            "--zk_string", str(src), "--mode", "PRINT_REASSIGNMENT",
            "--solver", "greedy", "--broker_hosts_to_remove", "h9",
        ])
    assert rc == 0 and "NEW ASSIGNMENT:" in out.getvalue()
    return out.getvalue()


@pytest.fixture()
def workdir(tmp_path, plan_text):
    """A fresh cluster copy + plan file + journal path per test."""
    cluster = tmp_path / "cluster.json"
    cluster.write_text(json.dumps(_cluster()))
    plan = tmp_path / "plan.json"
    plan.write_text(plan_text)
    return {
        "cluster": str(cluster),
        "plan": str(plan),
        "journal": str(tmp_path / "run.journal"),
        "report": str(tmp_path / "report.json"),
    }


def _execute(w, *extra):
    argv = ["--zk_string", w["cluster"], "--plan", w["plan"],
            "--journal", w["journal"], *extra]
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        rc = execute(argv)
    return rc, err.getvalue()


def _final_topics(w):
    with open(w["cluster"], "r", encoding="utf-8") as f:
        return {
            t: {int(p): list(r) for p, r in parts.items()}
            for t, parts in json.load(f)["topics"].items()
        }


# --- journal -----------------------------------------------------------------

def test_journal_round_trip_and_wave_split(tmp_path):
    path = str(tmp_path / "j")
    moves = [("t", p, [1, 2, 3]) for p in range(7)]
    j = ExecutionJournal.fresh(path, "hash", 3, moves)
    assert j.waves_total == 3
    assert [m[1] for m in j.wave(0)] == [0, 1, 2]
    assert [m[1] for m in j.wave(2)] == [6]
    j.commit_wave(2, skipped=[("t", 4)])
    loaded = ExecutionJournal.load(path)
    assert loaded.waves_committed == 2
    assert loaded.skipped == [("t", 4)]
    assert loaded.moves == moves
    assert loaded.status == "in-progress"
    loaded.complete()
    assert ExecutionJournal.load(path).status == "complete"


def test_fresh_journal_move_order_is_canonical(tmp_path):
    """The wave partition is a function of plan CONTENT: scrambled upstream
    ordering freezes into (topic, partition) order — but ``load`` replays a
    journal file's order verbatim, committed wave boundaries included."""
    path = str(tmp_path / "j")
    scrambled = [("tb", 1, [2]), ("ta", 5, [3]), ("tb", 0, [1]),
                 ("ta", 2, [4])]
    j = ExecutionJournal.fresh(path, "hash", 2, scrambled)
    canonical = [("ta", 2, [4]), ("ta", 5, [3]), ("tb", 0, [1]),
                 ("tb", 1, [2])]
    assert j.moves == canonical
    assert ExecutionJournal.fresh(
        str(tmp_path / "j2"), "hash", 2, list(reversed(scrambled))
    ).moves == canonical
    # load() is verbatim: hand the file a NON-canonical order and the
    # in-flight run must resume against exactly those waves.
    data = json.loads((tmp_path / "j").read_text())
    data["moves"] = [list(m) for m in reversed(canonical)]
    (tmp_path / "j").write_text(json.dumps(data))
    loaded = ExecutionJournal.load(path)
    assert loaded.moves == list(reversed(canonical))


def test_journal_rejects_corruption_and_bad_schema(tmp_path):
    p = tmp_path / "j"
    p.write_text("{not json")
    with pytest.raises(JournalError, match="corrupt"):
        ExecutionJournal.load(str(p))
    p.write_text(json.dumps({"version": 99}))
    with pytest.raises(JournalError, match="version"):
        ExecutionJournal.load(str(p))
    p.write_text(json.dumps({
        "version": 1, "plan": "h", "wave_size": 2, "status": "in-progress",
        "waves_committed": 9, "moves": [["t", 0, [1]]], "skipped": [],
    }))
    with pytest.raises(JournalError, match="committed"):
        ExecutionJournal.load(str(p))


def test_plan_fingerprint_is_whitespace_insensitive(workdir):
    plan_a, order_a = load_plan_file(workdir["plan"])
    bare = json.dumps({
        "partitions": [
            {"partition": p, "replicas": plan_a[t][p], "topic": t}
            for t in order_a for p in sorted(plan_a[t])
        ],
        "version": 1,
    }, indent=3)  # kalint: disable=KA005 -- building a scratch INPUT fixture, not emitting a plan
    from kafka_assigner_tpu.io.json_io import parse_reassignment_json

    parsed = parse_reassignment_json(bare)
    assert plan_fingerprint(parsed, list(parsed)) == \
        plan_fingerprint(plan_a, order_a)


def test_load_plan_file_accepts_bare_json_and_saved_stdout(
    workdir, tmp_path
):
    full, order = load_plan_file(workdir["plan"])
    bare_path = tmp_path / "bare.json"
    from kafka_assigner_tpu.io.json_io import format_reassignment_pairs

    bare_path.write_text(
        format_reassignment_pairs([(t, full[t]) for t in order])
    )
    bare, bare_order = load_plan_file(str(bare_path))
    assert bare == full and bare_order == order
    # The rollback section must NOT be what gets executed: a saved stdout
    # contains the CURRENT ASSIGNMENT first, and it differs from the plan.
    with open(workdir["plan"], "r", encoding="utf-8") as f:
        rollback = f.read().split("NEW ASSIGNMENT:")[0]
    from kafka_assigner_tpu.io.json_io import parse_reassignment_json

    current = parse_reassignment_json(rollback.split("\n", 1)[1].strip())
    assert current != full


# --- happy path --------------------------------------------------------------

def test_execute_ok_and_verify(workdir):
    rc, err = _execute(workdir, "--report-json", workdir["report"])
    assert rc == EXIT_OK, err
    assert "verify-after-move OK" in err
    plan, _ = load_plan_file(workdir["plan"])
    final = _final_topics(workdir)
    for t, parts in plan.items():
        for p, reps in parts.items():
            assert final[t][p] == reps
    with open(workdir["report"], "r", encoding="utf-8") as f:
        rep = json.load(f)
    counters = rep["metrics"]["counters"]
    assert counters["exec.waves"] >= 2          # a real multi-wave run
    assert counters["exec.moves"] >= counters["exec.waves"]
    assert counters["exec.verify"] == 1
    assert counters["zk.writes"] == counters["exec.waves"]
    assert "exec.wave_ms" in rep["metrics"]["histograms"]
    assert rep["plan"]["skipped_moves"] == []
    assert rep["plan"]["verify_mismatches"] == []
    assert [s for s in rep["spans"] if s["name"] == "exec/verify"]
    with open(workdir["journal"], "r", encoding="utf-8") as f:
        assert json.load(f)["status"] == "complete"


def test_execute_is_idempotent_when_converged(workdir):
    rc, _ = _execute(workdir)
    assert rc == EXIT_OK
    os.unlink(workdir["journal"])
    rc, err = _execute(workdir)
    assert rc == EXIT_OK
    assert "0 move(s) submitted" in err  # everything was a noop


def test_wave_size_flag_overrides_knob(workdir):
    rc, err = _execute(workdir, "--wave-size", "1")
    assert rc == EXIT_OK
    with open(workdir["journal"], "r", encoding="utf-8") as f:
        j = json.load(f)
    assert j["wave_size"] == 1
    assert len(j["moves"]) == -(-len(j["moves"]) // 1)  # one move per wave


# --- crash / resume ----------------------------------------------------------

def _baseline_final(workdir, tmp_path):
    base = str(tmp_path / "baseline.json")
    shutil.copy(workdir["cluster"], base)
    w = dict(workdir, cluster=base, journal=str(tmp_path / "b.journal"))
    rc, err = _execute(w)
    assert rc == EXIT_OK, err
    with open(base, "r", encoding="utf-8") as f:
        return f.read()


def test_kill_at_wave_boundary_resumes_byte_identical(
    workdir, tmp_path, monkeypatch
):
    base_final = _baseline_final(workdir, tmp_path)
    monkeypatch.setenv("KA_FAULTS_SPEC", "wave:1=crash")
    faults.reset()
    with pytest.raises(InjectedExecCrash):
        _execute(workdir)
    monkeypatch.delenv("KA_FAULTS_SPEC")
    faults.reset()
    with open(workdir["journal"], "r", encoding="utf-8") as f:
        j = json.load(f)
    assert j["status"] == "in-progress" and j["waves_committed"] == 1
    # Without --resume the interrupted journal is refused loudly.
    rc, err = _execute(workdir)
    assert rc == EXIT_VALIDATION
    assert "--resume" in err
    rc, err = _execute(workdir, "--resume")
    assert rc == EXIT_OK, err
    assert "resuming from journal" in err
    with open(workdir["cluster"], "r", encoding="utf-8") as f:
        assert f.read() == base_final


def test_resume_refuses_a_different_plan(workdir, tmp_path, monkeypatch):
    monkeypatch.setenv("KA_FAULTS_SPEC", "wave:1=crash")
    faults.reset()
    with pytest.raises(InjectedExecCrash):
        _execute(workdir)
    monkeypatch.delenv("KA_FAULTS_SPEC")
    faults.reset()
    plan, order = load_plan_file(workdir["plan"])
    t0 = order[0]
    p0 = sorted(plan[t0])[0]
    plan[t0][p0] = list(reversed(plan[t0][p0]))
    from kafka_assigner_tpu.io.json_io import format_reassignment_pairs

    with open(workdir["plan"], "w", encoding="utf-8") as f:
        f.write(format_reassignment_pairs([(t, plan[t]) for t in order]))
    rc, err = _execute(workdir, "--resume")
    assert rc == EXIT_VALIDATION
    assert "different plan" in err


def test_resume_without_journal_is_a_validation_error(workdir):
    rc, err = _execute(workdir, "--resume")
    assert rc == EXIT_VALIDATION
    assert "journal" in err


def test_interrupted_journal_of_another_plan_is_never_clobbered(
    workdir, tmp_path, monkeypatch
):
    monkeypatch.setenv("KA_FAULTS_SPEC", "wave:1=crash")
    faults.reset()
    with pytest.raises(InjectedExecCrash):
        _execute(workdir)
    monkeypatch.delenv("KA_FAULTS_SPEC")
    faults.reset()
    with open(workdir["journal"], "r", encoding="utf-8") as f:
        before = f.read()
    # A DIFFERENT plan pointed at the same journal path: refused, and the
    # interrupted run's committed-wave record survives untouched.
    from kafka_assigner_tpu.io.json_io import format_reassignment_pairs

    other = tmp_path / "other_plan.json"
    other.write_text(format_reassignment_pairs([("events", {0: [2, 1, 3]})]))
    rc, err = _execute(dict(workdir, plan=str(other)))
    assert rc == EXIT_VALIDATION
    assert "DIFFERENT plan" in err
    with open(workdir["journal"], "r", encoding="utf-8") as f:
        assert f.read() == before


def test_plan_time_skips_survive_a_crash_and_resume_degraded(
    workdir, tmp_path, monkeypatch
):
    """A best-effort run whose plan names an unresolvable topic, killed
    mid-execution: the plan-time skip is journaled, so the resumed run
    still exits DEGRADED with the skip named — never reclassified as a
    verify mismatch."""
    plan, order = load_plan_file(workdir["plan"])
    from kafka_assigner_tpu.io.json_io import format_reassignment_pairs

    mixed = tmp_path / "mixed_plan.json"
    mixed.write_text(format_reassignment_pairs(
        [("ghost", {0: [1, 2, 3]})] + [(t, plan[t]) for t in order]
    ))
    w = dict(workdir, plan=str(mixed), journal=str(tmp_path / "m.journal"))
    monkeypatch.setenv("KA_FAULTS_SPEC", "wave:1=crash")
    faults.reset()
    with pytest.raises(InjectedExecCrash):
        _execute(w, "--failure-policy", "best-effort")
    monkeypatch.delenv("KA_FAULTS_SPEC")
    faults.reset()
    with open(w["journal"], "r", encoding="utf-8") as f:
        assert ["ghost", 0] in json.load(f)["skipped"]
    rc, err = _execute(w, "--failure-policy", "best-effort", "--resume",
                       "--report-json", w["report"])
    assert rc == EXIT_DEGRADED, err
    with open(w["report"], "r", encoding="utf-8") as f:
        rep = json.load(f)
    assert ["ghost", 0] in rep["plan"]["skipped_moves"]
    assert rep["plan"]["verify_mismatches"] == []


# --- write seams -------------------------------------------------------------

def test_write_drop_reads_back_and_resubmits(workdir, monkeypatch):
    monkeypatch.setenv("KA_FAULTS_SPEC", "write:0=drop")
    faults.reset()
    rc, err = _execute(workdir, "--report-json", workdir["report"])
    assert rc == EXIT_OK, err
    assert "never a blind replay" in err
    with open(workdir["report"], "r", encoding="utf-8") as f:
        counters = json.load(f)["metrics"]["counters"]
    assert counters["exec.write_retries"] >= 1
    assert counters["faults.injected.drop"] == 1


def test_write_lost_strict_halts_resumably(workdir, monkeypatch, tmp_path):
    base_final = _baseline_final(workdir, tmp_path)
    monkeypatch.setenv("KA_FAULTS_SPEC", "write:0=lost")
    monkeypatch.setenv("KA_EXEC_POLL_TIMEOUT", "0.3")
    faults.reset()
    rc, err = _execute(workdir)
    assert rc == EXIT_EXECUTE
    assert "--resume" in err
    # The acked-but-lost write left the OLD assignment complete: nothing
    # stranded, and the journal resumes to the byte-identical final state.
    monkeypatch.delenv("KA_FAULTS_SPEC")
    monkeypatch.setenv("KA_EXEC_POLL_TIMEOUT", "10")
    faults.reset()
    rc, err = _execute(workdir, "--resume")
    assert rc == EXIT_OK, err
    with open(workdir["cluster"], "r", encoding="utf-8") as f:
        assert f.read() == base_final


def test_write_lost_best_effort_degrades_with_accounting(
    workdir, monkeypatch
):
    initial = _final_topics(workdir)
    monkeypatch.setenv("KA_FAULTS_SPEC", "write:0=lost")
    monkeypatch.setenv("KA_EXEC_POLL_TIMEOUT", "0.3")
    faults.reset()
    rc, err = _execute(workdir, "--failure-policy", "best-effort",
                       "--report-json", workdir["report"])
    assert rc == EXIT_DEGRADED, err
    with open(workdir["report"], "r", encoding="utf-8") as f:
        rep = json.load(f)
    assert rep["status"] == "degraded"
    skipped = rep["plan"]["skipped_moves"]
    assert skipped  # the lost wave's moves, named partition by partition
    final = _final_topics(workdir)
    for t, p in skipped:
        # A skipped move leaves its COMPLETE initial replica list — never
        # a partial state.
        assert final[t][int(p)] == initial[t][int(p)]


def test_converge_stall_retries_through(workdir, monkeypatch):
    monkeypatch.setenv("KA_FAULTS_SPEC", "converge:0=stall")
    faults.reset()
    rc, _ = _execute(workdir, "--report-json", workdir["report"])
    assert rc == EXIT_OK
    with open(workdir["report"], "r", encoding="utf-8") as f:
        counters = json.load(f)["metrics"]["counters"]
    assert counters["exec.retries"] >= 1
    assert counters["faults.injected.stall"] == 1


# --- verify-after-move -------------------------------------------------------

def test_external_drift_fails_verify(workdir, monkeypatch):
    monkeypatch.setenv("KA_FAULTS_SPEC", "wave:1=crash")
    faults.reset()
    with pytest.raises(InjectedExecCrash):
        _execute(workdir)
    monkeypatch.delenv("KA_FAULTS_SPEC")
    faults.reset()
    # Somebody else rewrites a partition the interrupted run had already
    # committed; the resumed run's verify pass must catch it.
    with open(workdir["journal"], "r", encoding="utf-8") as f:
        t0, p0, _ = json.load(f)["moves"][0]
    with open(workdir["cluster"], "r", encoding="utf-8") as f:
        snap = json.load(f)
    snap["topics"][t0][str(p0)] = [9] + snap["topics"][t0][str(p0)][1:]
    with open(workdir["cluster"], "w", encoding="utf-8") as f:
        json.dump(snap, f)  # kalint: disable=KA005 -- doctoring a test-fixture snapshot
    rc, err = _execute(workdir, "--resume", "--report-json",
                       workdir["report"])
    assert rc == EXIT_VERIFY
    assert "VERIFY MISMATCH" in err
    with open(workdir["report"], "r", encoding="utf-8") as f:
        rep = json.load(f)
    assert rep["plan"]["verify_mismatches"]
    assert rep["plan"]["verify_mismatches"][0]["topic"] == t0


def test_read_only_backend_is_refused():
    class ReadOnly:
        pass

    # ValueError (validation exit): refused before any journal exists.
    with pytest.raises(ValueError, match="cannot execute"):
        PlanExecutor(
            ReadOnly(), {"t": {0: [1]}}, ["t"], "/nonexistent/journal"
        ).execute()


def test_missing_plan_topic_strict_vs_best_effort(workdir, tmp_path):
    from kafka_assigner_tpu.io.json_io import format_reassignment_pairs

    ghost_plan = tmp_path / "ghost.json"
    ghost_plan.write_text(
        format_reassignment_pairs([("ghost", {0: [1, 2, 3]})])
    )
    w = dict(workdir, plan=str(ghost_plan),
             journal=str(tmp_path / "g.journal"))
    # Validation, not the resumable-halt code: no journal exists yet, so
    # exit 8's "--resume" promise would be a lie here.
    rc, err = _execute(w)
    assert rc == EXIT_VALIDATION
    assert "does not exist" in err
    assert not os.path.exists(w["journal"])
    rc, err = _execute(w, "--failure-policy", "best-effort")
    assert rc == EXIT_DEGRADED
    assert "skipping" in err


# --- usage / CLI surface -----------------------------------------------------

def test_usage_requires_plan_and_zk_string(capsys):
    assert execute([]) == 1
    assert "required" in capsys.readouterr().err


def test_journal_default_path_is_plan_derived(workdir):
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        rc = execute(["--zk_string", workdir["cluster"],
                      "--plan", workdir["plan"]])
    assert rc == EXIT_OK
    assert os.path.exists(workdir["plan"] + ".journal")


# --- degraded-run diff in the plan section (ISSUE 7 satellite) ---------------

def test_mode3_reports_unplanned_topics(workdir, tmp_path):
    report = str(tmp_path / "m3_report.json")
    err = io.StringIO()
    out = io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = run([
            "--zk_string", workdir["cluster"],
            "--mode", "PRINT_REASSIGNMENT", "--solver", "greedy",
            "--topics", "events,ghost", "--failure-policy", "best-effort",
            "--report-json", report,
        ])
    assert rc == EXIT_DEGRADED
    with open(report, "r", encoding="utf-8") as f:
        rep = json.load(f)
    assert rep["plan"]["unplanned_topics"] == ["ghost"]
    assert rep["metrics"]["gauges"]["ingest.topics_skipped"] == 1


# --- ka-execute --rollback (ISSUE 8 satellite) -------------------------------

def _canonical_snapshot_bytes(tmp_path, data):
    """The original cluster serialized through the snapshot writer — the
    byte-identity oracle for 'rollback restored the initial state' (the
    execution engine re-persists through the same writer)."""
    from kafka_assigner_tpu.io.base import BrokerInfo
    from kafka_assigner_tpu.io.snapshot import write_snapshot

    path = str(tmp_path / "canonical_initial.json")
    write_snapshot(
        path,
        [BrokerInfo(id=b["id"], host=b["host"], port=b["port"],
                    rack=b.get("rack")) for b in data["brokers"]],
        {t: {int(p): list(r) for p, r in parts.items()}
         for t, parts in data["topics"].items()},
    )
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


def test_rollback_restores_byte_identical_state(workdir, tmp_path):
    canonical = _canonical_snapshot_bytes(tmp_path, _cluster())
    initial = _final_topics(workdir)

    rc, _ = _execute(workdir)
    assert rc == EXIT_OK
    moved = _final_topics(workdir)
    assert moved != initial  # the forward run really moved replicas

    # Rollback through the same wave engine, default rollback journal.
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        rc = execute(["--zk_string", workdir["cluster"],
                      "--plan", workdir["plan"], "--rollback"])
    assert rc == EXIT_OK, err.getvalue()
    assert "verify-after-move OK" in err.getvalue()
    with open(workdir["cluster"], "r", encoding="utf-8") as f:
        assert f.read() == canonical  # byte-identical restore
    # Its own journal identity: the forward journal is untouched, the
    # rollback journal is complete.
    assert os.path.exists(workdir["plan"] + ".rollback.journal")
    with open(workdir["plan"] + ".rollback.journal", encoding="utf-8") as f:
        assert json.load(f)["status"] == "complete"


def test_rollback_refuses_bare_plan_json(tmp_path, capsys):
    bare = tmp_path / "bare_plan.json"
    bare.write_text(
        '{"partitions": [{"topic": "events", "partition": 0, '
        '"replicas": [1, 2, 3]}], "version": 1}'
    )
    cluster = tmp_path / "cluster.json"
    cluster.write_text(json.dumps(_cluster()))
    rc = execute(["--zk_string", str(cluster), "--plan", str(bare),
                  "--rollback"])
    assert rc == EXIT_VALIDATION
    assert "no 'CURRENT ASSIGNMENT:'" in capsys.readouterr().err


def test_load_plan_file_current_section(workdir):
    from kafka_assigner_tpu.io.json_io import parse_reassignment_json

    fwd, _ = load_plan_file(workdir["plan"])
    cur, _ = load_plan_file(workdir["plan"], section="current")
    with open(workdir["plan"], "r", encoding="utf-8") as f:
        text = f.read()
    snapshot_line = text.split("CURRENT ASSIGNMENT:", 1)[1].strip()
    snapshot_line = snapshot_line.splitlines()[0]
    assert cur == parse_reassignment_json(snapshot_line)
    assert cur != fwd  # the plan really changes something


def test_rollback_env_journal_gets_own_identity(workdir, tmp_path,
                                                monkeypatch):
    """KA_EXEC_JOURNAL must not make forward and rollback runs share one
    journal: the env default gets the rollback suffix too."""
    shared = str(tmp_path / "env.journal")
    monkeypatch.setenv("KA_EXEC_JOURNAL", shared)
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        rc = execute(["--zk_string", workdir["cluster"],
                      "--plan", workdir["plan"]])
    assert rc == EXIT_OK, err.getvalue()
    with contextlib.redirect_stderr(err):
        rc = execute(["--zk_string", workdir["cluster"],
                      "--plan", workdir["plan"], "--rollback"])
    assert rc == EXIT_OK, err.getvalue()
    assert os.path.exists(shared)
    assert os.path.exists(shared + ".rollback")
    with open(shared, encoding="utf-8") as f:
        fwd = json.load(f)
    with open(shared + ".rollback", encoding="utf-8") as f:
        rb = json.load(f)
    assert fwd["plan"] != rb["plan"]  # two journal identities, both complete
    assert fwd["status"] == rb["status"] == "complete"


# --- journal identity = (cluster, plan sha) — ISSUE 9 satellite --------------

def test_journal_persists_cluster_identity(tmp_path):
    path = str(tmp_path / "j")
    j = ExecutionJournal.fresh(path, "hash", 3, [("t", 0, [1])],
                               cluster="zk-a:2181")
    loaded = ExecutionJournal.load(path)
    assert loaded.cluster == "zk-a:2181"
    # legacy journals (no cluster field) load as cluster=None
    raw = json.loads((tmp_path / "j").read_text())
    del raw["cluster"]
    # kalint: disable=KA005 -- test fixture write, not a plan payload
    (tmp_path / "legacy").write_text(json.dumps(raw))
    assert ExecutionJournal.load(str(tmp_path / "legacy")).cluster is None


def test_resume_refuses_same_plan_on_a_different_cluster(
    workdir, tmp_path, monkeypatch
):
    """Two clusters executing BYTE-IDENTICAL plans must never cross-resume
    through one journal file: the journal is keyed by (cluster, plan sha),
    not the plan sha alone (the pre-ISSUE-9 collision)."""
    # interrupt a run on cluster A after one committed wave
    monkeypatch.setenv("KA_FAULTS_SPEC", "wave:1=crash")
    faults.reset()
    err = io.StringIO()
    with contextlib.redirect_stderr(err), pytest.raises(InjectedExecCrash):
        execute(["--zk_string", workdir["cluster"], "--plan",
                 workdir["plan"], "--journal", workdir["journal"]])
    monkeypatch.delenv("KA_FAULTS_SPEC")
    faults.reset()
    # cluster B: same initial metadata, so the SAME plan bytes apply — but
    # resuming through A's journal must be refused loudly
    other = tmp_path / "other_cluster.json"
    other.write_text(json.dumps(_cluster()))
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        rc = execute(["--zk_string", str(other), "--plan", workdir["plan"],
                      "--journal", workdir["journal"], "--resume"])
    assert rc == EXIT_VALIDATION
    assert "DIFFERENT cluster" in err.getvalue()
    # a FRESH run on cluster B through the same journal path is refused
    # too: the interrupted run's record must never be clobbered
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        rc = execute(["--zk_string", str(other), "--plan", workdir["plan"],
                      "--journal", workdir["journal"]])
    assert rc == EXIT_VALIDATION
    assert "DIFFERENT cluster" in err.getvalue()
    # the rightful owner still resumes to completion
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        rc = execute(["--zk_string", workdir["cluster"], "--plan",
                      workdir["plan"], "--journal", workdir["journal"],
                      "--resume"])
    assert rc == EXIT_OK, err.getvalue()


def test_legacy_clusterless_journal_still_resumes(workdir, monkeypatch):
    """Back-compat: a journal written before the cluster field existed
    (cluster=None) resumes under any cluster."""
    monkeypatch.setenv("KA_FAULTS_SPEC", "wave:1=crash")
    faults.reset()
    err = io.StringIO()
    with contextlib.redirect_stderr(err), pytest.raises(InjectedExecCrash):
        execute(["--zk_string", workdir["cluster"], "--plan",
                 workdir["plan"], "--journal", workdir["journal"]])
    monkeypatch.delenv("KA_FAULTS_SPEC")
    faults.reset()
    raw = json.loads(open(workdir["journal"]).read())
    raw["cluster"] = None
    with open(workdir["journal"], "w", encoding="utf-8") as f:
        # kalint: disable=KA005 -- test fixture write, not a plan payload
        json.dump(raw, f)
    rc, err = _execute(workdir, "--resume")
    assert rc == EXIT_OK, err
