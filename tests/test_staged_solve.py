"""Staged batched solve (vmapped placement + sequential leadership) must be
bit-identical to the scan-over-topics solve — including when the fast wave
strands a topic and the host rescue path re-places it through the full
fallback chain.
"""
from __future__ import annotations

import pytest

from kafka_assigner_tpu.assigner import TopicAssigner

from .test_invariants import make_cluster


def _solve_both(monkeypatch, topics, live, rack_map, rf=-1):
    monkeypatch.delenv("KA_STAGED_SOLVE", raising=False)
    sequential = TopicAssigner("tpu").generate_assignments(
        topics, live, rack_map, rf
    )
    monkeypatch.setenv("KA_STAGED_SOLVE", "1")
    staged = TopicAssigner("tpu").generate_assignments(topics, live, rack_map, rf)
    monkeypatch.delenv("KA_STAGED_SOLVE")
    return sequential, staged


def test_staged_matches_sequential(monkeypatch):
    current, live, rack_map = make_cluster(0, 16, 32, 3, 4)
    topics = [(f"t{i}", current) for i in range(5)]
    sequential, staged = _solve_both(monkeypatch, topics, live, rack_map)
    assert sequential == staged


def test_staged_matches_on_decommission(monkeypatch):
    current, live, rack_map = make_cluster(1, 20, 48, 3, 5, remove=2)
    topics = [(f"topic-{i}", current) for i in range(3)]
    sequential, staged = _solve_both(monkeypatch, topics, live, rack_map)
    assert sequential == staged


def test_staged_rescue_path_matches(monkeypatch):
    # Rack-unaware striped 10 -> 8 decommission: the fast wave strands this
    # (the balance fallback completes it), so in a mixed batch the staged
    # solver must rescue exactly that topic and still match the sequential
    # solve bit-for-bit.
    n, p, rf = 10, 50, 3
    base = list(range(n))
    strander = {q: [base[(q + i) % n] for i in range(rf)] for q in range(p)}
    live = set(base[2:])
    # an easy same-broker-set topic: striped over the live set
    lv = sorted(live)
    easy = {q: [lv[(q + i) % len(lv)] for i in range(rf)] for q in range(p)}
    topics = [("easy-0", easy), ("strander", strander), ("easy-1", easy)]
    sequential, staged = _solve_both(monkeypatch, topics, live, {})
    assert sequential == staged


def test_staged_infeasible_raises_same_error(monkeypatch):
    # Truly infeasible (RF == racks, singleton rack too small): both paths
    # must raise the reference's error.
    brokers = {1, 2, 3, 4}
    racks = {1: "a", 2: "b", 3: "b", 4: "b"}
    current = {q: [1 + (q + i) % 4 for i in range(2)] for q in range(10)}
    topics = [("t", current)]
    monkeypatch.setenv("KA_STAGED_SOLVE", "1")
    with pytest.raises(ValueError, match="could not be fully assigned"):
        TopicAssigner("tpu").generate_assignments(topics, brokers, racks, -1)
    monkeypatch.delenv("KA_STAGED_SOLVE")
    with pytest.raises(ValueError, match="could not be fully assigned"):
        TopicAssigner("tpu").generate_assignments(topics, brokers, racks, -1)
