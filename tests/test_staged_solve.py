"""Staged batched solve (vmapped placement + sequential leadership) must be
bit-identical to the scan-over-topics solve — including when the fast wave
strands a topic and the host rescue path re-places it through the full
fallback chain.
"""
from __future__ import annotations

import pytest

from kafka_assigner_tpu.assigner import TopicAssigner

from .test_invariants import make_cluster


def _solve_both(monkeypatch, topics, live, rack_map, rf=-1):
    monkeypatch.delenv("KA_STAGED_SOLVE", raising=False)
    sequential = TopicAssigner("tpu").generate_assignments(
        topics, live, rack_map, rf
    )
    monkeypatch.setenv("KA_STAGED_SOLVE", "1")
    staged = TopicAssigner("tpu").generate_assignments(topics, live, rack_map, rf)
    monkeypatch.delenv("KA_STAGED_SOLVE")
    return sequential, staged


def test_staged_matches_sequential(monkeypatch):
    current, live, rack_map = make_cluster(0, 16, 32, 3, 4)
    topics = [(f"t{i}", current) for i in range(5)]
    sequential, staged = _solve_both(monkeypatch, topics, live, rack_map)
    assert sequential == staged


def test_staged_matches_on_decommission(monkeypatch):
    current, live, rack_map = make_cluster(1, 20, 48, 3, 5, remove=2)
    topics = [(f"topic-{i}", current) for i in range(3)]
    sequential, staged = _solve_both(monkeypatch, topics, live, rack_map)
    assert sequential == staged


def test_staged_matches_with_rf_override(monkeypatch):
    # RF decrease (2 -> 1) and increase (2 -> 3) through both batched paths.
    current, live, rack_map = make_cluster(5, 12, 32, 2, 4)
    topics = [(f"t{i}", current) for i in range(3)]
    for rf in (1, 3):
        sequential, staged = _solve_both(monkeypatch, topics, live, rack_map, rf)
        assert sequential == staged, rf


def test_staged_rescue_path_matches(monkeypatch):
    # Rack-unaware striped 10 -> 8 decommission: the fast wave strands this
    # (the balance fallback completes it), so in a mixed batch the staged
    # solver must rescue exactly that topic and still match the sequential
    # solve bit-for-bit.
    n, p, rf = 10, 50, 3
    base = list(range(n))
    strander = {q: [base[(q + i) % n] for i in range(rf)] for q in range(p)}
    live = set(base[2:])
    # an easy same-broker-set topic: striped over the live set
    lv = sorted(live)
    easy = {q: [lv[(q + i) % len(lv)] for i in range(rf)] for q in range(p)}
    topics = [("easy-0", easy), ("strander", strander), ("easy-1", easy)]
    sequential, staged = _solve_both(monkeypatch, topics, live, {})
    assert sequential == staged


def test_staged_infeasible_raises_same_error(monkeypatch):
    # Truly infeasible (RF == racks, singleton rack too small): both paths
    # must raise the reference's error.
    brokers = {1, 2, 3, 4}
    racks = {1: "a", 2: "b", 3: "b", 4: "b"}
    current = {q: [1 + (q + i) % 4 for i in range(2)] for q in range(10)}
    topics = [("t", current)]
    monkeypatch.setenv("KA_STAGED_SOLVE", "1")
    with pytest.raises(ValueError, match="could not be fully assigned"):
        TopicAssigner("tpu").generate_assignments(topics, brokers, racks, -1)
    monkeypatch.delenv("KA_STAGED_SOLVE")
    with pytest.raises(ValueError, match="could not be fully assigned"):
        TopicAssigner("tpu").generate_assignments(topics, brokers, racks, -1)


# Property: staged == sequential over randomized clusters. Shapes are pinned
# to one compile bucket (brokers pad 16, partitions pad 32) so hypothesis
# examples reuse the first compile instead of paying one per shape.
try:
    from hypothesis import given, settings, strategies as st

    @settings(max_examples=12, deadline=None)
    @given(st.integers(0, 10**6))
    def test_staged_equality_property(seed):
        import random

        rng = random.Random(seed)
        n = rng.randint(9, 16)
        p = rng.randint(17, 32)
        rf = rng.randint(1, 3)
        racks = rng.randint(max(rf, 2), 5)
        current, live, rack_map = make_cluster(
            seed, n, p, rf, racks, remove=rng.randint(0, 2)
        )
        topics = [(f"t{i}", current) for i in range(rng.randint(1, 3))]
        import os

        from kafka_assigner_tpu.assigner import TopicAssigner as TA

        os.environ.pop("KA_STAGED_SOLVE", None)
        try:
            sequential = TA("tpu").generate_assignments(
                topics, live, rack_map, -1
            )
            seq_err = None
        except ValueError as e:
            sequential, seq_err = None, str(e)
        os.environ["KA_STAGED_SOLVE"] = "1"
        try:
            try:
                staged = TA("tpu").generate_assignments(
                    topics, live, rack_map, -1
                )
                st_err = None
            except ValueError as e:
                staged, st_err = None, str(e)
        finally:
            os.environ.pop("KA_STAGED_SOLVE", None)
        assert sequential == staged and seq_err == st_err
except ImportError:  # hypothesis is optional
    pass
