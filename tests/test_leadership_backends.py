"""Differential tests for the leadership-ordering backends.

The batched solve defaults to the host-native C++ pass
(``native/leadership.py:order_many``) while the on-device scan
(``ops/assignment.py:leadership_order`` / ``order_batched``) remains the
jit-internal implementation (what-if sweep, single-topic assign) and the
no-toolchain fallback. The two must stay byte-identical — including the
cross-topic Context counter carry — or the solver's output would depend on
which backend happened to be selected."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from kafka_assigner_tpu.ops.assignment import leadership_order, order_batched

try:
    from kafka_assigner_tpu.native.leadership import order_many

    from kafka_assigner_tpu.native.build import (
        build_native_library,
        load_native_library,
    )

    # Load-only since ISSUE 14: tests are a startup site, so build first.
    build_native_library()
    load_native_library()
    HAVE_NATIVE = True
except Exception:
    HAVE_NATIVE = False

pytestmark = pytest.mark.skipif(
    not HAVE_NATIVE, reason="native library unbuildable in this environment"
)


def _random_batch(rng, b, p_pad, n, rf):
    """Placed batches with mixed real sizes and mixed per-row counts (padded
    rows count 0, exactly as placement emits them)."""
    acc = np.full((b, p_pad, rf), -1, np.int32)
    cnt = np.zeros((b, p_pad), np.int32)
    p_reals = np.zeros(b, np.int32)
    jhashes = np.zeros(b, np.int64)
    for t in range(b):
        p = int(rng.integers(0, p_pad + 1))
        p_reals[t] = p
        jhashes[t] = int(rng.integers(0, 2**31 - 1))
        for row in range(p):
            m = int(rng.integers(1, rf + 1))
            acc[t, row, :m] = rng.choice(n, m, replace=False)
            cnt[t, row] = m
    return acc, cnt, jhashes, p_reals


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_order_many_matches_device_scan(seed):
    rng = np.random.default_rng(seed)
    b, p_pad, n, rf = 7, 24, 16, 3
    acc, cnt, jhashes, p_reals = _random_batch(rng, b, p_pad, n, rf)
    counters0 = rng.integers(0, 6, (n, rf)).astype(np.int32)

    got_o, got_c = order_many(acc, cnt, jhashes, p_reals, counters0)

    # Reference: the device scan, topic by topic, carrying the counter slab
    # (order_batched is the jit equivalent; drive leadership_order directly
    # so a bug in order_batched's scan plumbing can't mask one here).
    c = jnp.asarray(counters0)
    for t in range(b):
        o, c = leadership_order(
            jnp.asarray(acc[t]), jnp.asarray(cnt[t]), c,
            jnp.int32(jhashes[t] % (2**31)), rf,
        )
        np.testing.assert_array_equal(
            got_o[t], np.asarray(o), err_msg=f"topic {t} ordering diverged"
        )
    np.testing.assert_array_equal(got_c, np.asarray(c))
    # input slab must not be mutated (order_many takes a private copy)
    assert counters0.max() <= 6


def test_order_many_matches_order_batched():
    rng = np.random.default_rng(9)
    b, p_pad, n, rf = 4, 16, 12, 3
    acc, cnt, jhashes, p_reals = _random_batch(rng, b, p_pad, n, rf)
    counters0 = rng.integers(0, 3, (n, rf)).astype(np.int32)
    got_o, got_c = order_many(acc, cnt, jhashes, p_reals, counters0)
    ref_o, ref_c = order_batched(
        jnp.asarray(acc), jnp.asarray(cnt), jnp.asarray(counters0),
        jnp.asarray(jhashes.astype(np.int32)), rf=rf,
    )
    np.testing.assert_array_equal(got_o, np.asarray(ref_o))
    np.testing.assert_array_equal(got_c, np.asarray(ref_c))


def test_device_backend_env_matches_native(monkeypatch):
    # End-to-end: the same multi-topic solve through KA_LEADERSHIP=device
    # must reproduce the native default byte-for-byte (incl. leader order).
    from kafka_assigner_tpu.assigner import TopicAssigner

    topics = [
        (f"t{i}", {p: [1 + (p + i) % 8, 1 + (p + i + 3) % 8] for p in range(6)})
        for i in range(4)
    ]
    live = set(range(1, 21))  # cap slack: 48 replicas, 20 brokers
    racks = {b: f"r{b % 4}" for b in live}
    monkeypatch.delenv("KA_LEADERSHIP", raising=False)
    default = TopicAssigner("tpu").generate_assignments(topics, live, racks, -1)
    monkeypatch.setenv("KA_LEADERSHIP", "device")
    device = TopicAssigner("tpu").generate_assignments(topics, live, racks, -1)
    assert default == device


def test_unknown_backend_value_warns_and_defaults(monkeypatch, capsys):
    from kafka_assigner_tpu.native.leadership import leadership_backend

    monkeypatch.setenv("KA_LEADERSHIP", "gpu")
    assert leadership_backend() in ("native", "device")
    assert "KA_LEADERSHIP" in capsys.readouterr().err
