"""The four reference scenario tests (``KafkaTopicAssignerTest.java:18-157``),
parametrized over every solver backend — the behavioral contract both the
greedy oracle and the TPU solver must satisfy."""
from __future__ import annotations

import pytest

from kafka_assigner_tpu.assigner import TopicAssigner

from .helpers import verify_and_count

def _available_solvers():
    names = ["greedy"]
    try:  # the TPU solver lands after the oracle; skip cleanly until then
        from kafka_assigner_tpu.solvers.base import get_solver

        get_solver("tpu")
        names.append("tpu")
    except Exception:
        pass
    return names


SOLVERS = _available_solvers()


@pytest.fixture(params=SOLVERS)
def assigner(request) -> TopicAssigner:
    return TopicAssigner(solver=request.param)


def test_rack_aware_expansion(assigner):
    # KafkaTopicAssignerTest.java:18-57 — 3 -> 5 brokers across racks a/b/c/a/b.
    current = {0: [10, 11], 1: [11, 12], 2: [12, 10], 3: [10, 12]}
    brokers = {10, 11, 12, 13, 14}
    racks = {10: "a", 11: "b", 12: "c", 13: "a", 14: "b"}
    new = assigner.generate_assignment("test", current, brokers, racks, -1)
    counts = verify_and_count(current, new, 1)
    # 5 brokers, 4 partitions, RF=2: two brokers serve 1 replica, three serve 2.
    assert sorted(counts.values()) == [1, 1, 2, 2, 2]


def test_cluster_expansion(assigner):
    # KafkaTopicAssignerTest.java:59-82 — 3 -> 4 brokers, no racks.
    current = {0: [10, 11], 1: [11, 12], 2: [12, 10], 3: [10, 12]}
    brokers = {10, 11, 12, 13}
    new = assigner.generate_assignment("test", current, brokers, {}, -1)
    counts = verify_and_count(current, new, 1)
    # 4 brokers, 4 partitions, RF=2: every broker serves exactly 2 replicas.
    assert all(c == 2 for c in counts.values()), counts


def test_decommission(assigner):
    # KafkaTopicAssignerTest.java:84-122 — remove broker 12.
    current = {0: [10, 11], 1: [11, 12], 2: [12, 13], 3: [13, 10]}
    brokers = {10, 11, 13}
    new = assigner.generate_assignment("test", current, brokers, {}, -1)
    counts = verify_and_count(current, new, 1)
    assert 12 not in counts
    # 3 brokers, 4 partitions, RF=2: one broker serves 2, the other two serve 3.
    assert sorted(counts.values()) == [2, 3, 3]


def test_replacement(assigner):
    # KafkaTopicAssignerTest.java:124-157 — swap broker 12 for 13.
    current = {0: [10, 11], 1: [11, 12], 2: [12, 10], 3: [10, 12]}
    brokers = {10, 11, 13}
    new = assigner.generate_assignment("test", current, brokers, {}, -1)
    counts = verify_and_count(current, new, 1)
    assert 12 not in counts
    # Partition 0 never touched broker 12, so it must be byte-identical.
    assert new[0] == current[0]
    # Survivors stay put; the replacement may be joined by either live peer.
    assert 11 in new[1] and (10 in new[1] or 13 in new[1])
    assert 10 in new[2] and (11 in new[2] or 13 in new[2])
    assert 10 in new[3] and (11 in new[3] or 13 in new[3])


def test_rf_inference_uniformity(assigner):
    # KafkaTopicAssigner.java:55-62 — non-uniform RF with desired=-1 must fail.
    current = {0: [10, 11], 1: [11]}
    with pytest.raises(ValueError, match="unexpected replication factor"):
        assigner.generate_assignment("test", current, {10, 11, 12}, {}, -1)


def test_rf_bounds(assigner):
    # KafkaTopicAssigner.java:65-69.
    with pytest.raises(ValueError, match="positive replication factor"):
        assigner.generate_assignment("test", {}, {10, 11}, {}, -1)
    with pytest.raises(ValueError, match="higher replication factor"):
        assigner.generate_assignment("test", {0: [10, 11]}, {10, 11}, {}, 3)


def test_rf_increase(assigner):
    # --desired_replication_factor above current: orphans fill the new slots.
    current = {0: [10], 1: [11], 2: [12], 3: [10]}
    brokers = {10, 11, 12, 13}
    new = assigner.generate_assignment("test", current, brokers, {}, 2)
    counts = verify_and_count(current, new, 1)
    assert all(len(r) == 2 for r in new.values())
    assert sum(counts.values()) == 8


def test_infeasible_rack_constraint(assigner):
    # RF=2 but a single rack: the rack-exclusivity gate makes this unsolvable
    # (KafkaAssignmentStrategy.java:183-184 hard error).
    current = {0: [10, 11], 1: [11, 10]}
    racks = {10: "a", 11: "a", 12: "a"}
    with pytest.raises(ValueError, match="could not be fully assigned"):
        assigner.generate_assignment("test", current, {10, 11, 12}, racks, -1)
