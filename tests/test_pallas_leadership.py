"""The Pallas leadership kernel must be bit-identical to the XLA scan
implementation (interpret mode on CPU; the same kernel lowers to real TPU)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from kafka_assigner_tpu.ops.assignment import leadership_order
from kafka_assigner_tpu.ops.pallas_leadership import leadership_order_pallas


@pytest.mark.parametrize("seed,rf", [(0, 1), (0, 2), (0, 3), (1, 3), (0, 4)])
def test_kernel_matches_xla(seed, rf):
    rng = np.random.default_rng(seed)
    p, n = 40, 32
    acc = np.full((p, rf), -1, np.int32)
    cnt = np.zeros(p, np.int32)
    for i in range(p):
        c = int(rng.integers(0, rf + 1))  # includes partial/empty rows
        cnt[i] = c
        if c:
            acc[i, :c] = rng.choice(n, c, replace=False)
    counters = rng.integers(0, 7, (n, rf)).astype(np.int32)
    jh = int(rng.integers(0, 2**30))

    o1, c1 = leadership_order(
        jnp.asarray(acc), jnp.asarray(cnt), jnp.asarray(counters),
        jnp.int32(jh), rf,
    )
    o2, c2 = leadership_order_pallas(
        jnp.asarray(acc), jnp.asarray(cnt), jnp.asarray(counters),
        jnp.int32(jh), rf, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))


def test_solver_end_to_end_with_pallas_flag(monkeypatch):
    # Full solve parity with the kernel enabled. The flag is a *static jit
    # argument* (read per call), so the on/off paths compile separately and
    # this comparison is between genuinely different executables.
    from kafka_assigner_tpu.assigner import TopicAssigner

    current = {p: [10 + (p + i) % 6 for i in range(3)] for p in range(12)}
    live = set(range(10, 18))
    racks = {b: f"r{b % 4}" for b in live}

    monkeypatch.setenv("KA_PALLAS_LEADERSHIP", "1")
    with_pallas = TopicAssigner("tpu").generate_assignment("t", current, live, racks, -1)
    monkeypatch.delenv("KA_PALLAS_LEADERSHIP")
    without = TopicAssigner("tpu").generate_assignment("t", current, live, racks, -1)
    assert with_pallas == without


def test_flag_routing_is_per_call(monkeypatch):
    # The env flag must take effect per solver call (static jit arg), not be
    # frozen into a shared compilation cache entry.
    # The spy below observes TRACING; the persistent program store (ISSUE 6)
    # deliberately skips retrace on a hit, so force the plain-jit dispatch
    # for this test. Store-side routing of the flag is covered separately:
    # use_pallas is a static argument and therefore part of the store key
    # (tests/test_programstore.py pins distinct-static => distinct-entry).
    monkeypatch.setenv("KA_PROGRAM_STORE", "0")
    from kafka_assigner_tpu.ops import assignment as ops
    from kafka_assigner_tpu.ops import pallas_leadership as pk

    seen = []
    real = pk.leadership_order_pallas

    def spy(*args, **kwargs):
        seen.append(True)
        return real(*args, **kwargs)

    monkeypatch.setattr(pk, "leadership_order_pallas", spy)
    from kafka_assigner_tpu.assigner import TopicAssigner

    current = {p: [20 + (p + i) % 5 for i in range(2)] for p in range(7)}
    live = set(range(20, 27))
    monkeypatch.setenv("KA_PALLAS_LEADERSHIP", "1")
    TopicAssigner("tpu").generate_assignment("flag-on", current, live, {}, -1)
    assert seen, "kernel was not engaged with the flag set"
    seen.clear()
    monkeypatch.delenv("KA_PALLAS_LEADERSHIP")
    TopicAssigner("tpu").generate_assignment("flag-off", current, live, {}, -1)
    assert not seen, "kernel ran with the flag unset"


def test_batched_solve_with_pallas_flag(monkeypatch):
    # The kernel also runs inside the batched scan (assign_many); results must
    # match the XLA-scan batched path bit-for-bit.
    from kafka_assigner_tpu.assigner import TopicAssigner

    current = {p: [30 + (p + i) % 8 for i in range(3)] for p in range(10)}
    live = set(range(30, 40))
    racks = {b: f"r{b % 5}" for b in live}
    topics = [(f"t{i}", current) for i in range(4)]

    monkeypatch.setenv("KA_PALLAS_LEADERSHIP", "1")
    with_pallas = TopicAssigner("tpu").generate_assignments(topics, live, racks, -1)
    monkeypatch.delenv("KA_PALLAS_LEADERSHIP")
    without = TopicAssigner("tpu").generate_assignments(topics, live, racks, -1)
    assert with_pallas == without


def test_kernel_multiblock_grid_matches_xla():
    # P > BLOCK_P forces a multi-step sequential grid: the VMEM counter alias
    # must carry across blocks exactly like the scan carry. (Interpret mode;
    # the same grid lowers to real TPU.)
    rng = np.random.default_rng(7)
    p, n, rf = 1024, 64, 3
    assert p > 512, "must exceed BLOCK_P to exercise the grid carry"
    acc = np.full((p, rf), -1, np.int32)
    cnt = np.full(p, rf, np.int32)
    for i in range(p):
        acc[i] = rng.choice(n, rf, replace=False)
    counters = rng.integers(0, 5, (n, rf)).astype(np.int32)
    jh = int(rng.integers(0, 2**30))

    o1, c1 = leadership_order(
        jnp.asarray(acc), jnp.asarray(cnt), jnp.asarray(counters),
        jnp.int32(jh), rf,
    )
    o2, c2 = leadership_order_pallas(
        jnp.asarray(acc), jnp.asarray(cnt), jnp.asarray(counters),
        jnp.int32(jh), rf, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))


@pytest.mark.parametrize("p", [520, 8, 1000])
def test_kernel_non_block_multiple_p_matches_xla(p):
    # p_pad is a multiple of 8 (models/problem.py:_pad8), NOT of BLOCK_P:
    # the grid must ceil-divide and mask the tail rows, or the final
    # p % BLOCK_P partitions silently get garbage orderings and skipped
    # counter updates (the round-3 review finding this test pins).
    rng = np.random.default_rng(11)
    n, rf = 32, 3
    acc = np.full((p, rf), -1, np.int32)
    cnt = np.full(p, rf, np.int32)
    for i in range(p):
        acc[i] = rng.choice(n, rf, replace=False)
    counters = rng.integers(0, 5, (n, rf)).astype(np.int32)
    jh = int(rng.integers(0, 2**30))

    o1, c1 = leadership_order(
        jnp.asarray(acc), jnp.asarray(cnt), jnp.asarray(counters),
        jnp.int32(jh), rf,
    )
    o2, c2 = leadership_order_pallas(
        jnp.asarray(acc), jnp.asarray(cnt), jnp.asarray(counters),
        jnp.int32(jh), rf, interpret=True,
    )
    assert o2.shape == (p, rf)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))


def test_batched_pallas_actually_engages(monkeypatch):
    # Regression pin for the restoration merge bug: _resolve_native_order
    # ignored use_pallas, so on boxes where the host C++ leadership backend
    # is buildable (the production default) KA_PALLAS_LEADERSHIP=1 silently
    # degraded to the native path in assign_many — outputs are identical by
    # design, so only the solver's leadership telemetry can catch it (the
    # same guard bench.py's pallas variant uses).
    from kafka_assigner_tpu.assigner import TopicAssigner

    current = {p: [40 + (p + i) % 7 for i in range(3)] for p in range(9)}
    live = set(range(40, 49))
    racks = {b: f"r{b % 3}" for b in live}
    topics = [(f"pt{i}", current) for i in range(3)]

    monkeypatch.setenv("KA_PALLAS_LEADERSHIP", "1")
    on = TopicAssigner("tpu")
    with_pallas = on.generate_assignments(topics, live, racks, -1)
    assert on.solver.last_leadership == "pallas"
    monkeypatch.delenv("KA_PALLAS_LEADERSHIP")
    off = TopicAssigner("tpu")
    without = off.generate_assignments(topics, live, racks, -1)
    assert off.solver.last_leadership in ("native", "device")
    assert with_pallas == without
