"""The lint gate runs inside tier-1: ``scripts/lint.sh`` must exit 0 on the
committed tree, and the kalint CLI must fail loudly (rule ID + file:line) on
a file that violates the house rules — the regression wire for the whole
static-analysis subsystem without separate CI plumbing."""
from __future__ import annotations

import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def test_lint_sh_is_green_on_the_tree():
    proc = subprocess.run(
        ["bash", str(ROOT / "scripts" / "lint.sh")],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_kalint_cli_fails_on_violations_with_rule_and_location(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\n"
        'mode = os.environ.get("KA_TYPO_KNOB")\n',
        encoding="utf-8",
    )
    proc = subprocess.run(
        [sys.executable, "-m", "kafka_assigner_tpu.analysis.kalint", str(bad)],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": str(ROOT)},
    )
    assert proc.returncode == 1
    assert "KA001" in proc.stdout and "KA003" in proc.stdout
    assert f"{bad}:2" in proc.stdout  # file:line in the finding
