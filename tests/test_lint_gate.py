"""The lint gate runs inside tier-1: ``scripts/lint.sh`` must exit 0 on the
committed tree, and the kalint CLI must fail loudly (rule ID + file:line) on
a file that violates the house rules — the regression wire for the whole
static-analysis subsystem without separate CI plumbing."""
from __future__ import annotations

import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def test_lint_sh_is_green_on_the_tree():
    proc = subprocess.run(
        ["bash", str(ROOT / "scripts" / "lint.sh")],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_kalint_cli_fails_on_violations_with_rule_and_location(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\n"
        'mode = os.environ.get("KA_TYPO_KNOB")\n',
        encoding="utf-8",
    )
    proc = subprocess.run(
        [sys.executable, "-m", "kafka_assigner_tpu.analysis.kalint", str(bad)],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": str(ROOT)},
    )
    assert proc.returncode == 1
    assert "KA001" in proc.stdout and "KA003" in proc.stdout
    assert f"{bad}:2" in proc.stdout  # file:line in the finding


def _kalint_env(extra=None):
    env = {"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": str(ROOT)}
    if extra:
        env.update(extra)
    return env


def _run_kalint(args, env=None):
    import time

    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "kafka_assigner_tpu.analysis.kalint", *args],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
        env=env or _kalint_env(),
    )
    return proc, time.perf_counter() - t0


def test_seeded_cross_module_ka002_chain_is_caught_with_explain():
    """ISSUE 12 acceptance: a host-sync in a helper called from a jitted
    entry in ANOTHER module is caught by the CLI, and --explain prints the
    full entry -> helper call chain."""
    proc, _ = _run_kalint([
        "--root", "tests/kalint_fixtures/xmod", "--no-cache",
        "--explain", "KA002",
    ])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "KA002" in proc.stdout
    assert "helper.py:7" in proc.stdout          # the sink, file:line
    out = proc.stdout
    assert out.index("entry.py::solve") < out.index("helper.py::bias"), out
    assert "time.time() wall clock" in out


def test_json_report_is_deterministic_and_machine_readable(tmp_path):
    import json

    out1, out2 = tmp_path / "a.json", tmp_path / "b.json"
    for out in (out1, out2):
        proc, _ = _run_kalint([
            "--root", "tests/kalint_fixtures/xmod", "--no-cache",
            "--format", "json", "--out", str(out),
        ])
        assert proc.returncode == 1
    assert out1.read_bytes() == out2.read_bytes()  # stable across runs
    payload = json.loads(out1.read_text())
    assert payload["schema_version"] == 1 and payload["count"] >= 1
    f = payload["findings"][0]
    assert f["rule"] == "KA002" and f["path"].endswith("helper.py")
    assert f["chain"][0].startswith("entry.py::solve")
    # deduped + sorted: (path, line, rule, col) keys are unique and ordered
    keys = [(d["path"], d["line"], d["rule"], d["col"])
            for d in payload["findings"]]
    assert keys == sorted(keys) and len(keys) == len(set(keys))


def test_analysis_cache_cold_then_warm_is_faster(tmp_path):
    """ISSUE 12 acceptance: the content-hash cache misses cold, hits warm,
    and the warm run is faster than the cold interprocedural pass."""
    env = _kalint_env({"KA_LINT_CACHE_DIR": str(tmp_path / "cache")})
    cold, t_cold = _run_kalint([], env=env)
    assert cold.returncode == 0, cold.stdout + cold.stderr
    assert "analysis cache miss" in cold.stderr
    warm, t_warm = _run_kalint([], env=env)
    assert warm.returncode == 0, warm.stdout + warm.stderr
    assert "analysis cache hit" in warm.stderr
    assert warm.stdout == cold.stdout  # served findings are identical
    assert t_warm < t_cold, (t_warm, t_cold)


def test_json_report_carries_the_rule_catalog(tmp_path):
    """ISSUE 17 satellite: the JSON payload lint.sh publishes as
    ``kalint_report.json`` (KA_LINT_REPORT=1 copies the warm run's bytes)
    must carry the full rule catalog — CI annotation steps map rule ids
    to meanings without re-importing kalint — including the new
    determinism layer."""
    import json

    out = tmp_path / "report.json"
    env = _kalint_env({"KA_LINT_CACHE": "1"})
    proc, _ = _run_kalint(["--format", "json", "--out", str(out)], env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(out.read_text())
    rules = payload["rules"]
    for rule in ("KA001", "KA024", "KA025", "KA026", "KA027", "KA028"):
        assert rule in rules and rules[rule], rule
    assert "unordered iteration" in rules["KA024"]


def test_sarif_carries_determinism_codeflows(tmp_path):
    """Every KA024-KA027 finding on the determinism fixture renders its
    source->sink chain as a SARIF codeFlow (the chain is the triage
    artifact: it names the sink the source reaches)."""
    import json

    out = tmp_path / "report.sarif"
    proc, _ = _run_kalint([
        "--root", "tests/kalint_fixtures/determinism", "--no-cache",
        "--format", "sarif", "--out", str(out),
    ])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    run = json.loads(out.read_text())["runs"][0]
    by_rule = {}
    for result in run["results"]:
        by_rule.setdefault(result["ruleId"], []).append(result)
    for rule in ("KA024", "KA025", "KA026", "KA027"):
        assert rule in by_rule, sorted(by_rule)
        for result in by_rule[rule]:
            (flow,) = result["codeFlows"]
            locs = flow["threadFlows"][0]["locations"]
            assert locs, result
            for loc in locs:
                msg = loc["location"]["message"]["text"]
                assert "::" in msg and "@" in msg  # key@line hops
    # the driver declares the whole catalog, determinism rules included
    declared = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"KA024", "KA025", "KA026", "KA027", "KA028"} <= declared
