"""The lint gate runs inside tier-1: ``scripts/lint.sh`` must exit 0 on the
committed tree, and the kalint CLI must fail loudly (rule ID + file:line) on
a file that violates the house rules — the regression wire for the whole
static-analysis subsystem without separate CI plumbing."""
from __future__ import annotations

import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def test_lint_sh_is_green_on_the_tree():
    proc = subprocess.run(
        ["bash", str(ROOT / "scripts" / "lint.sh")],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_kalint_cli_fails_on_violations_with_rule_and_location(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\n"
        'mode = os.environ.get("KA_TYPO_KNOB")\n',
        encoding="utf-8",
    )
    proc = subprocess.run(
        [sys.executable, "-m", "kafka_assigner_tpu.analysis.kalint", str(bad)],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": str(ROOT)},
    )
    assert proc.returncode == 1
    assert "KA001" in proc.stdout and "KA003" in proc.stdout
    assert f"{bad}:2" in proc.stdout  # file:line in the finding


def _kalint_env(extra=None):
    env = {"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": str(ROOT)}
    if extra:
        env.update(extra)
    return env


def _run_kalint(args, env=None):
    import time

    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "kafka_assigner_tpu.analysis.kalint", *args],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
        env=env or _kalint_env(),
    )
    return proc, time.perf_counter() - t0


def test_seeded_cross_module_ka002_chain_is_caught_with_explain():
    """ISSUE 12 acceptance: a host-sync in a helper called from a jitted
    entry in ANOTHER module is caught by the CLI, and --explain prints the
    full entry -> helper call chain."""
    proc, _ = _run_kalint([
        "--root", "tests/kalint_fixtures/xmod", "--no-cache",
        "--explain", "KA002",
    ])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "KA002" in proc.stdout
    assert "helper.py:7" in proc.stdout          # the sink, file:line
    out = proc.stdout
    assert out.index("entry.py::solve") < out.index("helper.py::bias"), out
    assert "time.time() wall clock" in out


def test_json_report_is_deterministic_and_machine_readable(tmp_path):
    import json

    out1, out2 = tmp_path / "a.json", tmp_path / "b.json"
    for out in (out1, out2):
        proc, _ = _run_kalint([
            "--root", "tests/kalint_fixtures/xmod", "--no-cache",
            "--format", "json", "--out", str(out),
        ])
        assert proc.returncode == 1
    assert out1.read_bytes() == out2.read_bytes()  # stable across runs
    payload = json.loads(out1.read_text())
    assert payload["schema_version"] == 1 and payload["count"] >= 1
    f = payload["findings"][0]
    assert f["rule"] == "KA002" and f["path"].endswith("helper.py")
    assert f["chain"][0].startswith("entry.py::solve")
    # deduped + sorted: (path, line, rule, col) keys are unique and ordered
    keys = [(d["path"], d["line"], d["rule"], d["col"])
            for d in payload["findings"]]
    assert keys == sorted(keys) and len(keys) == len(set(keys))


def test_analysis_cache_cold_then_warm_is_faster(tmp_path):
    """ISSUE 12 acceptance: the content-hash cache misses cold, hits warm,
    and the warm run is faster than the cold interprocedural pass."""
    env = _kalint_env({"KA_LINT_CACHE_DIR": str(tmp_path / "cache")})
    cold, t_cold = _run_kalint([], env=env)
    assert cold.returncode == 0, cold.stdout + cold.stderr
    assert "analysis cache miss" in cold.stderr
    warm, t_warm = _run_kalint([], env=env)
    assert warm.returncode == 0, warm.stdout + warm.stderr
    assert "analysis cache hit" in warm.stderr
    assert warm.stdout == cold.stdout  # served findings are identical
    assert t_warm < t_cold, (t_warm, t_cold)
