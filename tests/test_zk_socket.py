"""Real-socket ZooKeeper integration smoke (VERDICT r3 item 5): an
in-process server speaking the actual ZooKeeper jute wire protocol listens
on a real TCP port, and the CLI runs end-to-end through ``io/zk.py`` with
packets crossing the socket — the layer the reference leaves untested and
round 3 exercised only via in-memory fakes.

The server (``tests/jute_server.py``, shared with the golden-frame pins,
the ingest bench, and the chaos soak) implements the session handshake plus
the read subset (getChildren / getData / exists / ping / closeSession). The
in-tree wire client (``io/zkwire.py``) is exercised always; when ``kazoo``
is installed (not in this image) the same server is smoked through it too.
"""
from __future__ import annotations

import json
import socket
import threading
import time

import pytest

from kafka_assigner_tpu.io.zkwire import (
    MiniZkClient,
    NoNodeError,
    ZkWireError,
    parse_hosts,
)

# Re-exported: scripts/bench_zk_ingest.py and test_zk_golden_frames import
# the server from here (its home before tests/jute_server.py existed).
from .jute_server import JuteZkServer, cluster_tree  # noqa: F401


def _cluster_tree():
    return cluster_tree()


@pytest.fixture()
def zk_server():
    server = JuteZkServer(_cluster_tree())
    server.start()
    yield server
    server.shutdown()


def test_parse_hosts():
    assert parse_hosts("h1:2181,h2:2182") == ([("h1", 2181), ("h2", 2182)], "")
    assert parse_hosts("h1:2181/kafka") == ([("h1", 2181)], "/kafka")
    assert parse_hosts("h1") == ([("h1", 2181)], "")


def test_wire_client_reads_over_real_socket(zk_server):
    client = MiniZkClient(f"127.0.0.1:{zk_server.port}", timeout=5.0)
    client.start()
    try:
        assert client.get_children("/brokers/ids") == ["1", "2", "3", "4"]
        data, stat = client.get("/brokers/ids/1")
        assert json.loads(data)["host"] == "h1"
        assert stat.dataLength == len(data)
        with pytest.raises(NoNodeError):
            client.get("/brokers/ids/99")
        with pytest.raises(NoNodeError):
            client.get_children("/nope")
    finally:
        client.stop()
        client.close()


def test_wire_client_chroot(zk_server):
    # Same tree served under a chroot-style connect string: paths prefix.
    chrooted = JuteZkServer(
        {f"/kafka{p}": d for p, d in _cluster_tree().items()}
    )
    chrooted.start()
    try:
        client = MiniZkClient(f"127.0.0.1:{chrooted.port}/kafka", timeout=5.0)
        client.start()
        assert client.get_children("/brokers/topics") == ["events", "logs"]
        client.stop()
        client.close()
    finally:
        chrooted.shutdown()


def test_zk_backend_over_real_socket(zk_server, monkeypatch):
    from kafka_assigner_tpu.io.base import BrokerInfo
    from kafka_assigner_tpu.io.zk import ZkBackend

    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    backend = ZkBackend(f"127.0.0.1:{zk_server.port}")
    try:
        assert backend.brokers() == [
            BrokerInfo(1, "h1", 9092, "ra"),
            BrokerInfo(2, "h2", 9093, "rb"),  # endpoint-resolved
            BrokerInfo(3, "h3", 9092, "rc"),
            BrokerInfo(4, "h4", 9092, "ra"),
        ]
        assert backend.all_topics() == ["events", "logs"]
        assert backend.partition_assignment(["events"]) == {
            "events": {0: [1, 2, 3], 1: [2, 3, 4]}
        }
    finally:
        backend.close()


def test_cli_end_to_end_over_real_socket(zk_server, capsys, monkeypatch):
    # The VERDICT item itself: the CLI against io/zk.py with real packets on
    # a real TCP socket — rollback snapshot, solve, reassignment JSON.
    from kafka_assigner_tpu.cli import run_tool
    from kafka_assigner_tpu.io.json_io import parse_reassignment_json

    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    rc = run_tool([
        "--zk_string", f"127.0.0.1:{zk_server.port}",
        "--mode", "PRINT_REASSIGNMENT", "--solver", "tpu",
        "--broker_hosts_to_remove", "h4",
    ])
    captured = capsys.readouterr()
    assert rc == 0
    assert captured.out.startswith("CURRENT ASSIGNMENT:\n")
    payload = captured.out.split("NEW ASSIGNMENT:\n", 1)[1].strip()
    new = parse_reassignment_json(payload)
    assert set(new) == {"events", "logs"}
    for parts in new.values():
        for replicas in parts.values():
            assert 4 not in replicas  # h4 drained


def _dead_port() -> int:
    """A port that was just bound and released — connecting to it refuses
    (nothing listens) on any sane loopback."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_start_falls_through_refused_endpoint(zk_server):
    # The satellite fix: one refused endpoint must not kill the session
    # attempt while a healthy quorum member is listed right next to it.
    client = MiniZkClient(
        f"127.0.0.1:{_dead_port()},127.0.0.1:{zk_server.port}", timeout=5.0
    )
    client.start()
    try:
        assert client.get_children("/brokers/topics") == ["events", "logs"]
    finally:
        client.stop()
        client.close()


def test_start_exhausts_retries_loudly(monkeypatch, capsys):
    monkeypatch.setenv("KA_ZK_CONNECT_RETRIES", "2")
    client = MiniZkClient(
        f"127.0.0.1:{_dead_port()},127.0.0.1:{_dead_port()}", timeout=0.5
    )
    with pytest.raises(ZkWireError, match=r"after 2 pass\(es\)"):
        client.start()
    # The backoff pass warns on stderr — silent retries look like a hang.
    assert "connect pass 1/2 failed" in capsys.readouterr().err


def test_start_succeeds_on_retry_pass(monkeypatch):
    # Nothing listens on the reserved port for the first pass; a server
    # comes up on it mid-backoff and the second pass lands the session.
    monkeypatch.setenv("KA_ZK_CONNECT_RETRIES", "5")
    port = _dead_port()
    started = []

    def _bring_up():
        time.sleep(0.15)
        server = JuteZkServer(_cluster_tree(), port=port)
        server.start()
        started.append(server)

    threading.Thread(target=_bring_up, daemon=True).start()
    client = MiniZkClient(f"127.0.0.1:{port}", timeout=2.0)
    try:
        client.start()
        assert client.get_children("/brokers/topics") == ["events", "logs"]
        client.stop()
        client.close()
    finally:
        for server in started:
            server.shutdown()


def test_session_expired_handshake_retries_to_success(monkeypatch):
    # The previously-unexercised branch (ISSUE 5 satellite): the server
    # expires the first handshake (timeOut=0, sessionId=0); the connect-pass
    # loop treats it like any failed endpoint and the second pass lands.
    monkeypatch.setenv("KA_ZK_CONNECT_RETRIES", "3")
    server = JuteZkServer(_cluster_tree(), expire_handshakes=1)
    server.start()
    try:
        client = MiniZkClient(f"127.0.0.1:{server.port}", timeout=2.0)
        client.start()
        assert client.get_children("/brokers/topics") == ["events", "logs"]
        client.stop()
        client.close()
    finally:
        server.shutdown()


def test_session_expired_handshake_exhausts_loudly(monkeypatch, capsys):
    # Every pass expired: the final error must NAME the handshake-expiry
    # cause, not a generic connect failure.
    monkeypatch.setenv("KA_ZK_CONNECT_RETRIES", "2")
    server = JuteZkServer(_cluster_tree(), expire_handshakes=99)
    server.start()
    try:
        client = MiniZkClient(f"127.0.0.1:{server.port}", timeout=2.0)
        with pytest.raises(
            ZkWireError, match="session expired during handshake"
        ):
            client.start()
        assert "connect pass 1/2 failed" in capsys.readouterr().err
    finally:
        server.shutdown()


def test_connect_backoff_is_jittered(monkeypatch):
    # The satellite fix: the inter-pass backoff must carry jitter (0.5x-1.5x
    # the nominal exponential step) so a fleet of parallel workers does not
    # retry a flapped quorum member in lockstep.
    import random as random_mod

    from kafka_assigner_tpu.io import zkwire

    sleeps: list = []
    monkeypatch.setattr(zkwire.time, "sleep", sleeps.append)
    monkeypatch.setenv("KA_ZK_CONNECT_RETRIES", "3")

    def run_with_uniform(u):
        sleeps.clear()
        monkeypatch.setattr(random_mod, "random", lambda: u)
        client = MiniZkClient(f"127.0.0.1:{_dead_port()}", timeout=0.2)
        with pytest.raises(ZkWireError):
            client.start()
        return list(sleeps)

    # random()=0 -> exactly half the nominal step; random()=1 -> 1.5x.
    lo = run_with_uniform(0.0)
    hi = run_with_uniform(1.0)
    assert lo == [pytest.approx(0.05), pytest.approx(0.1)]
    assert hi == [pytest.approx(0.15), pytest.approx(0.3)]


def test_get_many_matches_serial_gets(zk_server, monkeypatch):
    paths = [f"/brokers/ids/{i}" for i in (1, 2, 3, 4)] + [
        "/brokers/topics/events", "/brokers/topics/logs"
    ]
    serial_client = MiniZkClient(f"127.0.0.1:{zk_server.port}", timeout=5.0)
    serial_client.start()
    monkeypatch.setenv("KA_ZK_PIPELINE", "1")  # window of one == serial
    try:
        serial = [serial_client.get(p) for p in paths]
        assert serial_client.get_many(paths) == serial
        for window in ("2", "3", "64"):
            monkeypatch.setenv("KA_ZK_PIPELINE", window)
            assert serial_client.get_many(paths) == serial
        # The session stays usable after a mid-batch missing znode.
        with pytest.raises(NoNodeError, match="/brokers/ids/99"):
            serial_client.get_many(
                ["/brokers/ids/1", "/brokers/ids/99", "/brokers/ids/2"]
            )
        assert serial_client.get("/brokers/ids/3") == serial[2]
    finally:
        serial_client.stop()
        serial_client.close()


def test_iter_get_abandonment_drains_the_window(zk_server, monkeypatch):
    # Breaking out of iter_get mid-batch must not poison the session: the
    # in-flight replies are drained on generator close, so the next serial
    # call sees only its own xid.
    monkeypatch.setenv("KA_ZK_PIPELINE", "8")
    client = MiniZkClient(f"127.0.0.1:{zk_server.port}", timeout=5.0)
    client.start()
    try:
        paths = [f"/brokers/ids/{i}" for i in (1, 2, 3, 4)]
        for i, item in enumerate(client.iter_get(paths)):
            if i == 0:
                break  # 3 replies still in flight
        data, _ = client.get("/brokers/ids/3")
        assert json.loads(data)["host"] == "h3"
        assert client.get_children("/brokers/topics") == ["events", "logs"]
    finally:
        client.stop()
        client.close()


def test_mode3_output_byte_identical_across_ingest_modes(
    zk_server, capsys, monkeypatch
):
    # The acceptance pin: pipelining and the ingest/encode overlap are pure
    # latency optimizations — stdout must stay byte-identical with the
    # window forced to one, the overlap disabled, and any chunk size.
    from kafka_assigner_tpu.cli import run_tool

    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    argv = [
        "--zk_string", f"127.0.0.1:{zk_server.port}",
        "--mode", "PRINT_REASSIGNMENT", "--solver", "tpu",
        "--broker_hosts_to_remove", "h4",
    ]
    assert run_tool(argv) == 0
    baseline = capsys.readouterr().out
    assert baseline.startswith("CURRENT ASSIGNMENT:\n")
    for env in (
        {"KA_ZK_PIPELINE": "1"},
        {"KA_ZK_OVERLAP": "0"},
        {"KA_ZK_INGEST_CHUNK": "1"},
        {"KA_ZK_PIPELINE": "2", "KA_ZK_INGEST_CHUNK": "1"},
    ):
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        assert run_tool(argv) == 0
        assert capsys.readouterr().out == baseline, env
        for k in env:
            monkeypatch.delenv(k)


def test_pipeline_metrics_in_run_report(zk_server, tmp_path, monkeypatch, capsys):
    # The obs wiring of this PR's tentpole: a live-wire mode-3 run reports
    # the pipelined-ingest telemetry in the schema-v1 artifact.
    import json as json_mod

    from kafka_assigner_tpu.cli import run_tool
    from kafka_assigner_tpu.obs import report as report_mod

    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    report_path = tmp_path / "report.json"
    rc = run_tool([
        "--zk_string", f"127.0.0.1:{zk_server.port}",
        "--mode", "PRINT_REASSIGNMENT", "--solver", "tpu",
        "--report-json", str(report_path),
    ])
    capsys.readouterr()
    assert rc == 0
    with open(report_path, "r", encoding="utf-8") as f:
        report = json_mod.load(f)
    assert report_mod.validate_report(report) == []
    counters = report["metrics"]["counters"]
    gauges = report["metrics"]["gauges"]
    # brokers() and the topic ingest each pipeline one batch.
    assert counters["zk.pipeline.batches"] >= 2
    assert counters["zk.pipeline.rtts_saved"] >= 1
    assert gauges["zk.pipeline.in_flight"] >= 2
    assert gauges["ingest.topics"] == 2
    assert "ingest.overlap_ms" in gauges
    paths = {s["path"] for s in report["spans"]}
    assert (
        "mode/PRINT_REASSIGNMENT/metadata/assignment/ingest/stream" in paths
    )
    assert "zk.pipeline.batch_ms" in report["metrics"]["histograms"]


def test_kazoo_against_real_socket(zk_server):
    # Runs wherever kazoo is actually installed (not this image): the same
    # jute server must satisfy the production-preferred client too.
    kazoo_client = pytest.importorskip("kazoo.client")
    zk = kazoo_client.KazooClient(
        hosts=f"127.0.0.1:{zk_server.port}", timeout=5.0
    )
    zk.start(timeout=5.0)
    try:
        assert sorted(zk.get_children("/brokers/topics")) == ["events", "logs"]
        data, _ = zk.get("/brokers/ids/1")
        assert json.loads(data)["host"] == "h1"
    finally:
        zk.stop()
        zk.close()
