"""Real-socket ZooKeeper integration smoke (VERDICT r3 item 5): an
in-process server speaking the actual ZooKeeper jute wire protocol listens
on a real TCP port, and the CLI runs end-to-end through ``io/zk.py`` with
packets crossing the socket — the layer the reference leaves untested and
round 3 exercised only via in-memory fakes.

The server implements the session handshake plus the read subset
(getChildren / getData / exists / ping / closeSession). The in-tree wire
client (``io/zkwire.py``) is exercised always; when ``kazoo`` is installed
(not in this image) the same server is smoked through it too.
"""
from __future__ import annotations

import json
import socket
import struct
import threading

import pytest

from kafka_assigner_tpu.io.zkwire import (
    MiniZkClient,
    NoNodeError,
    parse_hosts,
)


class JuteZkServer(threading.Thread):
    """Minimal single-purpose ZooKeeper server: serves a static znode tree
    over the real wire protocol. ``tree`` maps full znode path -> bytes
    (data) and directories are implied by children paths."""

    def __init__(self, tree):
        super().__init__(daemon=True)
        self.tree = dict(tree)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()

    # -- jute helpers -----------------------------------------------------

    @staticmethod
    def _buf(data):
        return struct.pack(">i", len(data)) + data

    @staticmethod
    def _stat(data_len, n_children):
        return struct.pack(
            ">qqqqiiiqiiq", 1, 1, 0, 0, 0, 0, 0, 0, data_len, n_children, 1
        )

    def _children(self, path):
        prefix = path.rstrip("/") + "/"
        names = {
            p[len(prefix):].split("/", 1)[0]
            for p in self.tree
            if p.startswith(prefix)
        }
        return sorted(names)

    def _exists(self, path):
        return path in self.tree or bool(self._children(path))

    # -- server loop ------------------------------------------------------

    def run(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn):
        try:
            frame = self._recv_frame(conn)
            if frame is None:
                return
            # ConnectRequest: proto, lastZxid, timeOut, sessionId, passwd
            # [+ readOnly byte for 3.4+ clients].
            _, _, timeout_ms, _ = struct.unpack(">iqiq", frame[:24])
            has_ro = len(frame) > 24 + 4 + 16
            resp = (
                struct.pack(">iiq", 0, timeout_ms, 0x1EAF)
                + self._buf(b"\x00" * 16)
                + (b"\x00" if has_ro else b"")
            )
            self._send_frame(conn, resp)
            while True:
                frame = self._recv_frame(conn)
                if frame is None:
                    return
                xid, op = struct.unpack(">ii", frame[:8])
                body = frame[8:]
                if op == 11:  # ping
                    self._send_frame(conn, struct.pack(">iqi", -2, 1, 0))
                    continue
                if op == -11:  # closeSession
                    self._send_frame(conn, struct.pack(">iqi", xid, 1, 0))
                    return
                (plen,) = struct.unpack(">i", body[:4])
                path = body[4:4 + plen].decode("utf-8")
                if op == 8:  # getChildren
                    kids = self._children(path)
                    if not self._exists(path):
                        self._send_frame(
                            conn, struct.pack(">iqi", xid, 1, -101)
                        )
                        continue
                    payload = struct.pack(">iqi", xid, 1, 0)
                    payload += struct.pack(">i", len(kids))
                    for k in kids:
                        payload += self._buf(k.encode("utf-8"))
                    self._send_frame(conn, payload)
                elif op == 4:  # getData
                    data = self.tree.get(path)
                    if data is None:
                        self._send_frame(
                            conn, struct.pack(">iqi", xid, 1, -101)
                        )
                        continue
                    payload = (
                        struct.pack(">iqi", xid, 1, 0)
                        + self._buf(data)
                        + self._stat(len(data), len(self._children(path)))
                    )
                    self._send_frame(conn, payload)
                elif op == 3:  # exists
                    if self._exists(path):
                        payload = struct.pack(">iqi", xid, 1, 0) + self._stat(
                            len(self.tree.get(path, b"")),
                            len(self._children(path)),
                        )
                    else:
                        payload = struct.pack(">iqi", xid, 1, -101)
                    self._send_frame(conn, payload)
                else:  # unimplemented op: loud error, not a hang
                    self._send_frame(conn, struct.pack(">iqi", xid, 1, -6))
        except (OSError, struct.error):
            pass
        finally:
            conn.close()

    @staticmethod
    def _recv_frame(conn):
        header = b""
        while len(header) < 4:
            chunk = conn.recv(4 - len(header))
            if not chunk:
                return None
            header += chunk
        (n,) = struct.unpack(">i", header)
        data = b""
        while len(data) < n:
            chunk = conn.recv(n - len(data))
            if not chunk:
                return None
            data += chunk
        return data

    @staticmethod
    def _send_frame(conn, payload):
        conn.sendall(struct.pack(">i", len(payload)) + payload)

    def shutdown(self):
        self._stop.set()
        self.sock.close()


def _cluster_tree():
    brokers = {
        "1": {"host": "h1", "port": 9092, "rack": "ra"},
        "2": {"host": None, "endpoints": ["PLAINTEXT://h2:9093"], "rack": "rb"},
        "3": {"host": "h3", "port": 9092, "rack": "rc"},
        "4": {"host": "h4", "port": 9092, "rack": "ra"},
    }
    topics = {
        "events": {"partitions": {"0": [1, 2, 3], "1": [2, 3, 4]}},
        "logs": {"partitions": {"0": [3, 4]}},
    }
    tree = {}
    for bid, meta in brokers.items():
        tree[f"/brokers/ids/{bid}"] = json.dumps(meta).encode()
    for t, meta in topics.items():
        tree[f"/brokers/topics/{t}"] = json.dumps(meta).encode()
    return tree


@pytest.fixture()
def zk_server():
    server = JuteZkServer(_cluster_tree())
    server.start()
    yield server
    server.shutdown()


def test_parse_hosts():
    assert parse_hosts("h1:2181,h2:2182") == ([("h1", 2181), ("h2", 2182)], "")
    assert parse_hosts("h1:2181/kafka") == ([("h1", 2181)], "/kafka")
    assert parse_hosts("h1") == ([("h1", 2181)], "")


def test_wire_client_reads_over_real_socket(zk_server):
    client = MiniZkClient(f"127.0.0.1:{zk_server.port}", timeout=5.0)
    client.start()
    try:
        assert client.get_children("/brokers/ids") == ["1", "2", "3", "4"]
        data, stat = client.get("/brokers/ids/1")
        assert json.loads(data)["host"] == "h1"
        assert stat.dataLength == len(data)
        with pytest.raises(NoNodeError):
            client.get("/brokers/ids/99")
        with pytest.raises(NoNodeError):
            client.get_children("/nope")
    finally:
        client.stop()
        client.close()


def test_wire_client_chroot(zk_server):
    # Same tree served under a chroot-style connect string: paths prefix.
    chrooted = JuteZkServer(
        {f"/kafka{p}": d for p, d in _cluster_tree().items()}
    )
    chrooted.start()
    try:
        client = MiniZkClient(f"127.0.0.1:{chrooted.port}/kafka", timeout=5.0)
        client.start()
        assert client.get_children("/brokers/topics") == ["events", "logs"]
        client.stop()
        client.close()
    finally:
        chrooted.shutdown()


def test_zk_backend_over_real_socket(zk_server, monkeypatch):
    from kafka_assigner_tpu.io.base import BrokerInfo
    from kafka_assigner_tpu.io.zk import ZkBackend

    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    backend = ZkBackend(f"127.0.0.1:{zk_server.port}")
    try:
        assert backend.brokers() == [
            BrokerInfo(1, "h1", 9092, "ra"),
            BrokerInfo(2, "h2", 9093, "rb"),  # endpoint-resolved
            BrokerInfo(3, "h3", 9092, "rc"),
            BrokerInfo(4, "h4", 9092, "ra"),
        ]
        assert backend.all_topics() == ["events", "logs"]
        assert backend.partition_assignment(["events"]) == {
            "events": {0: [1, 2, 3], 1: [2, 3, 4]}
        }
    finally:
        backend.close()


def test_cli_end_to_end_over_real_socket(zk_server, capsys, monkeypatch):
    # The VERDICT item itself: the CLI against io/zk.py with real packets on
    # a real TCP socket — rollback snapshot, solve, reassignment JSON.
    from kafka_assigner_tpu.cli import run_tool
    from kafka_assigner_tpu.io.json_io import parse_reassignment_json

    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    rc = run_tool([
        "--zk_string", f"127.0.0.1:{zk_server.port}",
        "--mode", "PRINT_REASSIGNMENT", "--solver", "tpu",
        "--broker_hosts_to_remove", "h4",
    ])
    captured = capsys.readouterr()
    assert rc == 0
    assert captured.out.startswith("CURRENT ASSIGNMENT:\n")
    payload = captured.out.split("NEW ASSIGNMENT:\n", 1)[1].strip()
    new = parse_reassignment_json(payload)
    assert set(new) == {"events", "logs"}
    for parts in new.values():
        for replicas in parts.values():
            assert 4 not in replicas  # h4 drained


def test_kazoo_against_real_socket(zk_server):
    # Runs wherever kazoo is actually installed (not this image): the same
    # jute server must satisfy the production-preferred client too.
    kazoo_client = pytest.importorskip("kazoo.client")
    zk = kazoo_client.KazooClient(
        hosts=f"127.0.0.1:{zk_server.port}", timeout=5.0
    )
    zk.start(timeout=5.0)
    try:
        assert sorted(zk.get_children("/brokers/topics")) == ["events", "logs"]
        data, _ = zk.get("/brokers/ids/1")
        assert json.loads(data)["host"] == "h1"
    finally:
        zk.stop()
        zk.close()
