"""Slow-marked wrapper around ``scripts/bench_daemon_load.py`` (ISSUE 14
acceptance): a real ``ka-daemon`` subprocess driven at concurrency
{1, 8, 64} under the batched dispatcher AND the ``KA_DISPATCH=0`` lock —
the script itself asserts batched solve-bound p99@64 <= 3x the
single-client p99 (measured from the daemon's own /metrics histograms)
and byte-identity of every response against fresh-process solo baselines.
Kept out of tier-1 (the lock-mode comparison point alone queues ~64 full
solves); the fast coalescing cycle is the tier-1
``scripts/dispatch_smoke.py`` lint-gate smoke."""
from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_daemon_load(tmp_path):
    out = tmp_path / "BENCH_daemon_load.json"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "bench_daemon_load.py"),
         "--out", str(out)],
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    report = json.loads(out.read_text())
    assert report["headline"]["pass"] is True
    assert report["headline"]["batched_ratio_64_vs_1"] <= 3.0
