"""The wire client's write subset (ISSUE 7): create/setData/delete/exists
over a real TCP socket, the write-safety rule (never pipelined, never
blindly replayed — reconnect, read back, decide), the pipelined
``iter_children`` fan-out with session-reestablishment replay, and the
live-ZK execution path end to end (``ka-execute`` against the jute server's
simulated controller)."""
from __future__ import annotations

import contextlib
import io
import json
import os

import pytest

from kafka_assigner_tpu import faults
from kafka_assigner_tpu.cli import EXIT_OK, execute
from kafka_assigner_tpu.errors import ExecuteError
from kafka_assigner_tpu.io.zk import ZkBackend
from kafka_assigner_tpu.io.zkwire import (
    MiniZkClient,
    NodeExistsError,
    NoNodeError,
)
from kafka_assigner_tpu.io.json_io import format_reassignment_pairs

from .jute_server import JuteZkServer, cluster_tree, cluster_tree_with_states


@pytest.fixture(autouse=True)
def _fresh_injector():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def zk_server():
    server = JuteZkServer(cluster_tree(), controller_delay_ops=1)
    server.start()
    yield server
    server.shutdown()


def _client(server):
    c = MiniZkClient(f"127.0.0.1:{server.port}")
    c.start()
    return c


# --- the write opcodes over a real socket ------------------------------------

def test_create_set_delete_exists_round_trip(zk_server):
    # A neutral path: /admin/reassign_partitions would wake the server's
    # simulated controller, which deletes the znode after applying it.
    c = _client(zk_server)
    try:
        assert c.exists("/wtest") is None
        path = c.create("/wtest", b'{"version":1}')
        assert path == "/wtest"
        assert c.exists("/wtest") is not None
        data, _ = c.get("/wtest")
        assert data == b'{"version":1}'
        with pytest.raises(NodeExistsError):
            c.create("/wtest", b"other")
        c.set_data("/wtest", b'{"version":2}')
        data, _ = c.get("/wtest")
        assert data == b'{"version":2}'
        c.delete("/wtest")
        assert c.exists("/wtest") is None
        with pytest.raises(NoNodeError):
            c.set_data("/ghost", b"x")
    finally:
        c.stop()
        c.close()
    assert zk_server.write_ops == {"create": 1, "setData": 1, "delete": 1}


def test_dropped_write_reply_is_not_blindly_replayed(zk_server, monkeypatch):
    """A reply-scope drop DURING a setData: the server applied the write,
    the client lost the ack. The write-safety rule demands reconnect →
    read-back → DECIDE: the read-back shows the bytes landed, so the client
    must NOT re-issue — the server sees exactly one setData op."""
    monkeypatch.setenv("KA_ZK_SESSION_RETRIES", "2")
    c = _client(zk_server)
    try:
        c.create("/wnode", b"v1")
        faults.install(faults.FaultInjector(
            faults.parse_spec("reply:0=drop")
        ))
        # fresh client so the injector is picked up at construction
    finally:
        c.stop()
        c.close()
    c = _client(zk_server)
    err = io.StringIO()
    try:
        with contextlib.redirect_stderr(err):
            c.set_data("/wnode", b"v2")
        data, _ = c.get("/wnode")
        assert data == b"v2"
    finally:
        c.stop()
        c.close()
    assert "read-back shows it landed" in err.getvalue()
    assert zk_server.write_ops["setData"] == 1  # applied EXACTLY once
    faults.install(None)


def test_unsent_write_is_reissued_after_readback(zk_server, monkeypatch):
    """The other half of read-back-then-decide: the transport dies BEFORE
    the frame reaches the server, the read-back shows nothing landed, and
    the client re-issues — one applied write, after one visible retry."""
    monkeypatch.setenv("KA_ZK_SESSION_RETRIES", "2")
    c = _client(zk_server)
    real_send = MiniZkClient._send_frame
    state = {"broken": True}

    def flaky_send(self, payload):
        if state["broken"] and b"wnode2" in payload:
            state["broken"] = False
            self._sock.close()
            raise ConnectionResetError("wire cut before send")
        return real_send(self, payload)

    monkeypatch.setattr(MiniZkClient, "_send_frame", flaky_send)
    err = io.StringIO()
    try:
        with contextlib.redirect_stderr(err):
            c.create("/wnode2", b"payload")
        data, _ = c.get("/wnode2")
        assert data == b"payload"
    finally:
        c.stop()
        c.close()
    assert zk_server.write_ops["create"] == 1


def test_create_makepath_materializes_parents(zk_server):
    """Real ZK refuses a create under a missing parent (the jute server
    does too); ``makepath=True`` must materialize the chain shallowest
    first — the semantics ZkBackend.apply_assignment relies on for
    /admin/reassign_partitions on a fresh cluster."""
    c = _client(zk_server)
    try:
        with pytest.raises(NoNodeError):
            c.create("/deep/nested/node", b"x")
        c.create("/deep/nested/node", b"x", makepath=True)
        data, _ = c.get("/deep/nested/node")
        assert data == b"x"
        assert c.exists("/deep") is not None
        assert c.exists("/deep/nested") is not None
    finally:
        c.stop()
        c.close()
    assert zk_server.write_ops["create"] == 3  # two parents + the node


# --- pipelined getChildren fan-out -------------------------------------------

def test_iter_children_matches_serial(zk_server, monkeypatch):
    monkeypatch.setenv("KA_ZK_PIPELINE", "4")
    c = _client(zk_server)
    try:
        paths = ["/brokers/ids", "/brokers/topics", "/brokers",
                 "/brokers/ids", "/brokers/topics"]
        piped = list(c.iter_children(paths))
        serial = [c.get_children(p) for p in paths]
        assert piped == serial
        assert piped[0] == ["1", "2", "3", "4"]
    finally:
        c.stop()
        c.close()


def test_iter_children_missing_ok_yields_none(zk_server):
    c = _client(zk_server)
    try:
        out = list(c.iter_children(
            ["/brokers/ids", "/ghost", "/brokers/topics"], missing_ok=True
        ))
        assert out[0] == ["1", "2", "3", "4"]
        assert out[1] is None
        assert out[2] == ["events", "logs"]
        with pytest.raises(NoNodeError):
            list(c.iter_children(["/brokers/ids", "/ghost"]))
    finally:
        c.stop()
        c.close()


@pytest.mark.parametrize("spec", ["reply:2=drop", "reply:3=trunc"])
def test_iter_children_replays_only_unanswered_reads(
    zk_server, monkeypatch, spec
):
    """Session death mid-window: the fan-out re-establishes and re-issues
    ONLY the not-yet-yielded children reads — output identical to an
    uninterrupted run (the read-path replay contract now covers
    getChildren too)."""
    monkeypatch.setenv("KA_ZK_PIPELINE", "3")
    monkeypatch.setenv("KA_ZK_SESSION_RETRIES", "2")
    paths = ["/brokers/ids", "/brokers/topics", "/brokers",
             "/brokers/ids", "/brokers/topics", "/brokers"]
    c = _client(zk_server)
    try:
        clean = list(c.iter_children(paths))
    finally:
        c.stop()
        c.close()
    faults.install(faults.FaultInjector(faults.parse_spec(spec)))
    c = _client(zk_server)
    err = io.StringIO()
    try:
        with contextlib.redirect_stderr(err):
            healed = list(c.iter_children(paths))
    finally:
        c.stop()
        c.close()
    assert healed == clean
    assert "re-establishing" in err.getvalue()
    faults.install(None)


# --- the live-ZK execution path ----------------------------------------------

def _wire_env(monkeypatch):
    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    monkeypatch.setenv("KA_EXEC_WAVE_SIZE", "2")
    monkeypatch.setenv("KA_EXEC_POLL_INTERVAL", "0.01")
    monkeypatch.setenv("KA_EXEC_POLL_TIMEOUT", "10")


@pytest.mark.parametrize("treefn", [cluster_tree, cluster_tree_with_states])
def test_ka_execute_against_live_zk(tmp_path, monkeypatch, treefn):
    """End to end over the real wire protocol: plan file → waves written to
    /admin/reassign_partitions → the simulated controller applies them →
    convergence observed (topic znodes; plus ISR state znodes when the
    layout has them) → verify-after-move OK."""
    _wire_env(monkeypatch)
    server = JuteZkServer(treefn(), controller_delay_ops=1)
    server.start()
    try:
        plan = {
            "events": {0: [4, 3, 2], 1: [1, 2, 3]},
            "logs": {0: [2, 1]},
        }
        plan_path = tmp_path / "plan.json"
        plan_path.write_text(format_reassignment_pairs(
            [(t, plan[t]) for t in plan]
        ))
        err = io.StringIO()
        with contextlib.redirect_stderr(err):
            rc = execute([
                "--zk_string", f"127.0.0.1:{server.port}",
                "--plan", str(plan_path),
                "--journal", str(tmp_path / "j"),
            ])
        assert rc == EXIT_OK, err.getvalue()
        assert "verify-after-move OK" in err.getvalue()
        # The admin znode is cleaned up and the tree shows the targets.
        assert "/admin/reassign_partitions" not in server.tree
        events = json.loads(server.tree["/brokers/topics/events"])
        assert events["partitions"]["0"] == [4, 3, 2]
        if treefn is cluster_tree_with_states:
            state = json.loads(
                server.tree["/brokers/topics/events/partitions/0/state"]
            )
            assert state["isr"] == [4, 3, 2]
        assert server.write_ops["create"] >= 2  # one admin znode per wave
    finally:
        server.shutdown()


def test_apply_assignment_waits_out_a_stuck_admin_znode(monkeypatch):
    """An /admin/reassign_partitions left by another operator that never
    clears: apply_assignment must give up WITHIN the poll budget with the
    resumable ExecuteError, not hang."""
    _wire_env(monkeypatch)
    monkeypatch.setenv("KA_EXEC_POLL_TIMEOUT", "0.2")
    tree = cluster_tree()
    tree["/admin/reassign_partitions"] = b'{"version":1,"partitions":[]}'
    server = JuteZkServer(tree, controller_delay_ops=10 ** 9)
    server.start()
    backend = ZkBackend(f"127.0.0.1:{server.port}")
    try:
        with pytest.raises(ExecuteError, match="already in flight"):
            backend.apply_assignment({"events": {0: [4, 3, 2]}})
    finally:
        backend.close()
        server.shutdown()


def test_zk_backend_state_poll_reads_isr_from_state_znodes(monkeypatch):
    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    tree = cluster_tree_with_states()
    # A lagging follower: ISR smaller than the replica list.
    tree["/brokers/topics/events/partitions/0/state"] = json.dumps(
        {"isr": [1, 2], "leader": 1}
    ).encode()
    server = JuteZkServer(tree)
    server.start()
    backend = ZkBackend(f"127.0.0.1:{server.port}")
    try:
        state = backend.read_assignment_state(["events", "logs", "ghost"])
        assert state["events"][0].replicas == [1, 2, 3]
        assert state["events"][0].isr == [1, 2]       # from the state znode
        assert state["logs"][0].isr == [3, 4]
        assert "ghost" not in state
    finally:
        backend.close()
        server.shutdown()
