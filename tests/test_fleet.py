"""The daemon-wide FleetScheduler (ISSUE 20): admission leases and the
crash-safe fleet move-budget ledger, most-degraded-first priority, TTL
lease expiry, the three ``fleet:*`` chaos seams, boot-time recovery of
interrupted controller actions / rollbacks / orphaned client ``/execute``
journals, and the persisted verdict memory that keeps hysteresis warm
across a daemon restart."""
from __future__ import annotations

import json
import os
import time

import pytest

from kafka_assigner_tpu import faults
from kafka_assigner_tpu.daemon import AssignerDaemon
from kafka_assigner_tpu.daemon.fleet import FleetScheduler
from kafka_assigner_tpu.exec.journal import (
    ExecutionJournal,
    plan_fingerprint,
)
from kafka_assigner_tpu.faults.inject import FaultInjector, parse_spec
from kafka_assigner_tpu.io.json_io import format_reassignment_json

from .test_controller import (
    controller_daemon,
    imbalanced_snapshot,
    topics_of,
)
from .test_daemon import req


@pytest.fixture(autouse=True)
def _fresh_injector():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(autouse=True)
def _fleet_env(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_DAEMON_RESYNC_INTERVAL", "0.2")
    monkeypatch.setenv("KA_DAEMON_JOURNAL_DIR", str(tmp_path))
    # Park the loop: tests drive tick() by hand for determinism.
    monkeypatch.setenv("KA_CONTROLLER_INTERVAL", "3600")
    monkeypatch.setenv("KA_CONTROLLER_COOLDOWN", "0")
    monkeypatch.setenv("KA_CONTROLLER_CONFIRMATIONS", "2")
    monkeypatch.setenv("KA_CONTROLLER_MAX_MOVES", "32")
    monkeypatch.setenv("KA_EXEC_POLL_INTERVAL", "0.01")


def ready_scheduler():
    """A FleetScheduler with the recovery gate already cleared (an empty
    scan — exactly what a fresh journal dir produces at boot)."""
    fs = FleetScheduler()
    fs.recover({})
    return fs


# --- the admission lease API -------------------------------------------------

def test_admission_defers_until_recovery_ran():
    fs = FleetScheduler()
    status, info = fs.acquire("a", moves=1, sha="ab" * 32)
    assert status == "deferred"
    assert info["reason"] == "recovery pending"
    fs.recover({})
    status, _ = fs.acquire("a", moves=1, sha="ab" * 32)
    assert status == "granted"


def test_concurrency_cap_and_release():
    fs = ready_scheduler()
    status, lease = fs.acquire("a", moves=2, sha="aa" * 32)
    assert status == "granted" and lease["kind"] == "action"
    status, info = fs.acquire("b", moves=2, sha="bb" * 32)
    assert status == "deferred"
    assert info["holders"] == ["a"] and info["max_concurrent"] == 1
    assert fs.release("a") is True
    status, _ = fs.acquire("b", moves=2, sha="bb" * 32)
    assert status == "granted"


def test_budget_hold_and_refund(monkeypatch):
    monkeypatch.setenv("KA_FLEET_MAX_MOVES", "10")
    fs = ready_scheduler()
    assert fs.acquire("a", moves=8, sha="aa" * 32)[0] == "granted"
    fs.release("a")  # no refund: the 8 moves stay charged
    status, info = fs.acquire("b", moves=8, sha="bb" * 32)
    assert status == "budget-hold"
    assert info["window_moves"] == 8 and info["max_moves"] == 10
    # A refunded release (single-flight refusal: nothing moved) returns
    # the reservation.
    assert fs.acquire("b", moves=2, sha="bb" * 32)[0] == "granted"
    fs.release("b", refund=True)
    assert fs.acquire("c", moves=2, sha="cc" * 32)[0] == "granted"
    assert fs.view()["window"]["moves"] == 10


def test_most_degraded_cluster_preempts_the_healthier_one():
    fs = ready_scheduler()
    assert fs.acquire("a", moves=1, sha="aa" * 32, score=1.0)[0] \
        == "granted"
    # b (much worse off) asks while a holds: denied on concurrency, but
    # its want is now registered.
    assert fs.acquire("b", moves=1, sha="bb" * 32, score=5.0)[0] \
        == "deferred"
    fs.release("a")
    # The slot is free, but the worse-off cluster wins it.
    status, info = fs.acquire("a", moves=1, sha="aa" * 32, score=1.0)
    assert status == "preempted"
    assert info["winner"] == "b" and info["winner_score"] == 5.0
    assert fs.acquire("b", moves=1, sha="bb" * 32, score=5.0)[0] \
        == "granted"


def test_lease_ttl_expires_a_crashed_holder(monkeypatch):
    monkeypatch.setenv("KA_FLEET_LEASE_TTL", "0.1")
    fs = ready_scheduler()
    assert fs.acquire("a", moves=1, sha="aa" * 32)[0] == "granted"
    time.sleep(0.15)
    # No heartbeat inside the TTL: the slot moves on.
    assert fs.acquire("b", moves=1, sha="bb" * 32)[0] == "granted"
    # The stale holder's release is a loud no-op, not a corruption.
    assert fs.release("a") is False
    assert "lease-expired" in [
        e["decision"] for e in fs.view()["decisions"]
    ]


def test_heartbeat_keeps_a_live_holder_alive(monkeypatch):
    monkeypatch.setenv("KA_FLEET_LEASE_TTL", "0.2")
    fs = ready_scheduler()
    assert fs.acquire("a", moves=1, sha="aa" * 32)[0] == "granted"
    for _ in range(3):
        time.sleep(0.1)
        fs.heartbeat("a")
    assert fs.acquire("b", moves=1, sha="bb" * 32)[0] == "deferred"
    assert fs.release("a") is True


# --- the persisted ledger ----------------------------------------------------

def test_ledger_persists_leases_and_budget_across_instances(monkeypatch):
    monkeypatch.setenv("KA_FLEET_MAX_MOVES", "10")
    fs1 = ready_scheduler()
    assert fs1.acquire("a", moves=8, sha="aa" * 32)[0] == "granted"
    # A second scheduler over the same journal dir (a restarted daemon)
    # sees the lease AND the charge.
    fs2 = ready_scheduler()
    assert fs2.acquire("b", moves=1, sha="bb" * 32)[0] == "deferred"
    assert fs2.release("a") is True
    assert fs2.acquire("b", moves=8, sha="bb" * 32)[0] == "budget-hold"


def test_corrupt_ledger_starts_fresh_loudly(tmp_path):
    (tmp_path / "ka-fleet.json").write_text("{torn!")
    fs = ready_scheduler()
    assert fs.acquire("a", moves=1, sha="aa" * 32)[0] == "granted"


def test_ledger_torn_seam_discards_the_read(monkeypatch):
    fs1 = ready_scheduler()
    assert fs1.acquire("a", moves=4, sha="aa" * 32)[0] == "granted"
    faults.install(FaultInjector(parse_spec("fleet:0=ledger-torn")))
    fs2 = ready_scheduler()
    # The torn read is discarded wholesale: no half-trusted leases.
    assert fs2.view()["leases"] == {}
    assert fs2.acquire("b", moves=1, sha="bb" * 32)[0] == "granted"


def test_lease_expire_seam_sweeps_every_lease():
    fs = ready_scheduler()
    assert fs.acquire("a", moves=1, sha="aa" * 32)[0] == "granted"
    faults.install(FaultInjector(parse_spec("fleet:0=lease-expire")))
    assert fs.acquire("b", moves=1, sha="bb" * 32)[0] == "granted"
    assert fs.release("a") is False  # loud no-op: the seam expired it


# --- crafting interrupted runs for recovery ----------------------------------

HOT_ORIG = {str(p): [1, 2] for p in range(4)}
EVENTS_ORIG = [1, 2, 3]
EVENTS_NEW = [2, 3, 4]


def _plan_text():
    return (
        "CURRENT ASSIGNMENT:\n"
        + format_reassignment_json(
            {"events": {0: list(EVENTS_ORIG)}}, topic_order=["events"]
        )
        + "\nNEW ASSIGNMENT:\n"
        + format_reassignment_json(
            {"events": {0: list(EVENTS_NEW)}}, topic_order=["events"]
        )
        + "\n"
    )


def _forward_sha():
    return plan_fingerprint({"events": {0: list(EVENTS_NEW)}}, ["events"])


def _rollback_sha():
    return plan_fingerprint({"events": {0: list(EVENTS_ORIG)}}, ["events"])


def _write_journal(tmp_path, fname, plan_hash, moves, *, cluster,
                   waves_committed=0):
    j = ExecutionJournal(
        str(tmp_path / fname), plan_hash, 8, moves,
        waves_committed=waves_committed, cluster=cluster,
    )
    j.save()
    return j.path


def _write_record(tmp_path, sha, *, aborted):
    path = tmp_path / f"ka-controller-default-{sha[:12]}.action.json"
    path.write_text(json.dumps({
        "version": 1, "cluster": "default", "sha": sha,
        "moves": 3, "aborted": aborted, "plan_text": _plan_text(),
    }))
    return str(path)


def _journal_files(tmp_path):
    return sorted(
        p for p in os.listdir(tmp_path)
        if p.endswith(".journal") or p.endswith(".action.json")
    )


# --- boot-time recovery ------------------------------------------------------

def test_orphaned_execute_journal_resumes_at_boot(tmp_path):
    """The single-cluster bugfix: a journal from a killed client
    ``/execute`` used to sit invisible until a client passed resume=1 —
    now the daemon's own boot scan finishes it, under journal
    authority."""
    snap = imbalanced_snapshot(tmp_path)
    sha = _forward_sha()
    path = _write_journal(
        tmp_path, f"ka-execute-default-{sha[:12]}.journal", sha,
        [("events", 0, list(EVENTS_NEW))], cluster=snap,
    )
    with controller_daemon(snap) as (d, sup):
        view = d.fleet.view()
        assert view["recovered"] is True
        assert view["recovery"]["resumed"] == 1
        assert view["leases"] == {}  # the recovery lease was released
    assert topics_of(snap)["events"]["0"] == EVENTS_NEW
    assert ExecutionJournal.load(path).status == "complete"


def test_interrupted_forward_action_resumes_at_boot(tmp_path):
    snap = imbalanced_snapshot(tmp_path)
    sha = _forward_sha()
    path = _write_journal(
        tmp_path, f"ka-controller-default-{sha[:12]}.journal", sha,
        [("events", 0, list(EVENTS_NEW))], cluster=snap,
    )
    _write_record(tmp_path, sha, aborted=False)
    with controller_daemon(snap) as (d, sup):
        assert d.fleet.view()["recovery"]["resumed"] == 1
        # The forward journal completed to the fully-verified plan; the
        # record is gone (its action needs no more recovery).
        assert ExecutionJournal.load(path).status == "complete"
        assert not [
            p for p in _journal_files(tmp_path)
            if p.endswith(".action.json")
        ]
    assert topics_of(snap)["events"]["0"] == EVENTS_NEW


def test_killed_mid_rollback_resumes_the_rollback_at_boot(tmp_path):
    """ISSUE 20 satellite 1: a daemon killed mid-rollback converges to
    the PRE-ACTION bytes on restart, without operator intervention —
    byte-identical to what offline ``ka-execute --resume`` would do."""
    snap = imbalanced_snapshot(tmp_path)
    before = topics_of(snap)
    sha = _forward_sha()
    # The forward action fully applied (then the controller aborted)...
    data = json.loads(open(snap).read())
    data["topics"]["events"]["0"] = list(EVENTS_NEW)
    open(snap, "w").write(json.dumps(data))
    forward = _write_journal(
        tmp_path, f"ka-controller-default-{sha[:12]}.journal", sha,
        [("events", 0, list(EVENTS_NEW))], cluster=snap,
        waves_committed=1,
    )
    # ...and the kill landed with the rollback journal in-progress.
    _write_journal(
        tmp_path, f"ka-controller-default-{sha[:12]}.rollback.journal",
        _rollback_sha(), [("events", 0, list(EVENTS_ORIG))], cluster=snap,
    )
    _write_record(tmp_path, sha, aborted=True)
    with controller_daemon(snap) as (d, sup):
        assert d.fleet.view()["recovery"]["rolled_back"] == 1
        # Rollback recovery opens the controller breaker: the plan
        # failed before the kill — a restart grants no free probe.
        assert sup.controller.breaker_view()["state"] == "open"
    assert topics_of(snap) == before
    # The forward journal and the action record are superseded and gone;
    # only the completed rollback journal remains.
    left = _journal_files(tmp_path)
    assert not any(p.endswith(".action.json") for p in left)
    assert forward.split(os.sep)[-1] not in left
    rb = [p for p in left if p.endswith(".rollback.journal")]
    assert len(rb) == 1
    assert ExecutionJournal.load(str(tmp_path / rb[0])).status \
        == "complete"


def test_aborted_action_without_rollback_journal_rolls_back(tmp_path):
    """The kill landed between the abort decision and the rollback's
    first wave: the persisted record's ``aborted`` flag drives a FRESH
    rollback at boot."""
    snap = imbalanced_snapshot(tmp_path)
    before = topics_of(snap)
    sha = _forward_sha()
    data = json.loads(open(snap).read())
    data["topics"]["events"]["0"] = list(EVENTS_NEW)
    open(snap, "w").write(json.dumps(data))
    _write_journal(
        tmp_path, f"ka-controller-default-{sha[:12]}.journal", sha,
        [("events", 0, list(EVENTS_NEW))], cluster=snap,
        waves_committed=1,
    )
    _write_record(tmp_path, sha, aborted=True)
    with controller_daemon(snap) as (d, sup):
        assert d.fleet.view()["recovery"]["rolled_back"] == 1
    assert topics_of(snap) == before


def test_foreign_cluster_journal_is_left_untouched(tmp_path):
    snap = imbalanced_snapshot(tmp_path)
    sha = _forward_sha()
    path = _write_journal(
        tmp_path, f"ka-execute-default-{sha[:12]}.journal", sha,
        [("events", 0, list(EVENTS_NEW))],
        cluster="zk-elsewhere:2181/other",
    )
    with controller_daemon(snap) as (d, sup):
        assert d.fleet.view()["recovery"]["skipped"] == 1
    # Not resumed, not deleted: it belongs to a different cluster.
    assert ExecutionJournal.load(path).status == "in-progress"
    assert topics_of(snap)["events"]["0"] == EVENTS_ORIG


def test_recovery_crash_seam_retains_the_journal_for_the_next_boot(
    tmp_path,
):
    snap = imbalanced_snapshot(tmp_path)
    sha = _forward_sha()
    path = _write_journal(
        tmp_path, f"ka-execute-default-{sha[:12]}.journal", sha,
        [("events", 0, list(EVENTS_NEW))], cluster=snap,
    )
    faults.install(FaultInjector(parse_spec("fleet:0=recovery-crash")))
    with controller_daemon(snap) as (d, sup):
        view = d.fleet.view()
        assert view["recovery"]["failed"] == 1
        # The daemon still starts and admits: one wedged journal must
        # not invert the availability contract.
        assert view["recovered"] is True
    assert ExecutionJournal.load(path).status == "in-progress"
    # The next boot (fault cleared — a real kill -9 does not survive the
    # process) converges.
    faults.reset()
    with controller_daemon(snap) as (d, sup):
        assert d.fleet.view()["recovery"]["resumed"] == 1
    assert topics_of(snap)["events"]["0"] == EVENTS_NEW
    assert ExecutionJournal.load(path).status == "complete"


def test_orphan_action_record_is_swept_at_boot(tmp_path):
    snap = imbalanced_snapshot(tmp_path)
    record = _write_record(tmp_path, _forward_sha(), aborted=False)
    with controller_daemon(snap) as (d, sup):
        pass
    # No journal referenced it: the kill landed before wave 0 — nothing
    # moved, nothing to recover, the record is gone.
    assert not os.path.exists(record)
    assert topics_of(snap)["events"]["0"] == EVENTS_ORIG


# --- persisted verdict memory ------------------------------------------------

def test_hysteresis_streak_survives_a_daemon_restart(
    tmp_path, monkeypatch,
):
    monkeypatch.setenv("KA_CONTROLLER", "auto")
    monkeypatch.setenv("KA_CONTROLLER_CONFIRMATIONS", "3")
    snap = imbalanced_snapshot(tmp_path)
    with controller_daemon(snap) as (d, sup):
        assert sup.controller.tick()["streak"] == 1
        assert sup.controller.tick()["streak"] == 2
    # The restarted daemon re-confirms NOTHING: the persisted memory
    # carries the streak, so the third agreeing verdict acts.
    with controller_daemon(snap) as (d, sup):
        entry = sup.controller.tick()
        assert entry["decision"] == "acted", entry
    assert topics_of(snap) != {
        "hot": HOT_ORIG, "events": {"0": EVENTS_ORIG},
    }


def test_stale_verdict_memory_resets_loudly(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_CONTROLLER", "auto")
    (tmp_path / "ka-controller-default.verdict.json").write_text(
        json.dumps({"version": 99, "sha": "ff" * 32, "streak": 7})
    )
    snap = imbalanced_snapshot(tmp_path)
    with controller_daemon(snap) as (d, sup):
        entry = sup.controller.tick()
        # The streak restarts from scratch instead of trusting
        # confirmations made under different rules.
        assert entry["decision"] == "confirmed" and entry["streak"] == 1
        decisions = [
            e["decision"]
            for e in sup.controller_view()["decisions"]
        ]
        assert "memory-reset" in decisions


def test_acted_streak_reset_is_persisted(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_CONTROLLER", "auto")
    monkeypatch.setenv("KA_CONTROLLER_CONFIRMATIONS", "1")
    snap = imbalanced_snapshot(tmp_path)
    with controller_daemon(snap) as (d, sup):
        assert sup.controller.tick()["decision"] == "acted"
    raw = json.loads(
        (tmp_path / "ka-controller-default.verdict.json").read_text()
    )
    assert raw["streak"] == 0 and raw["sha"] is None


# --- the controller's fleet gate --------------------------------------------

def test_single_cluster_action_acquires_and_releases_the_lease(
    tmp_path, monkeypatch,
):
    monkeypatch.setenv("KA_CONTROLLER", "auto")
    monkeypatch.setenv("KA_CONTROLLER_CONFIRMATIONS", "1")
    snap = imbalanced_snapshot(tmp_path)
    with controller_daemon(snap) as (d, sup):
        assert sup.controller.tick()["decision"] == "acted"
        view = d.fleet.view()
        assert view["leases"] == {}  # held only for the action's span
        decisions = [e["decision"] for e in view["decisions"]]
        assert "granted" in decisions and "released" in decisions
        assert view["window"]["moves"] > 0


def test_fleet_denial_is_a_hold_that_keeps_hysteresis_warm(
    tmp_path, monkeypatch,
):
    monkeypatch.setenv("KA_CONTROLLER", "auto")
    monkeypatch.setenv("KA_CONTROLLER_CONFIRMATIONS", "1")
    monkeypatch.setenv("KA_FLEET_MAX_MOVES", "1")
    snap = imbalanced_snapshot(tmp_path)
    with controller_daemon(snap) as (d, sup):
        entry = sup.controller.tick()
        assert entry["decision"] == "hold"
        assert entry["reason"] == "fleet budget-hold"
        # Hysteresis stays warm through the denial: the NEXT admission
        # does not re-confirm from scratch.
        assert sup.controller.view()["streak"] >= 1
    assert topics_of(snap)["events"]["0"] == EVENTS_ORIG


# --- the HTTP surface --------------------------------------------------------

def test_get_fleet_endpoint_single_mode(tmp_path):
    snap = imbalanced_snapshot(tmp_path)
    with controller_daemon(snap) as (d, sup):
        s, body, _ = req(d.http_port, "GET", "/fleet")
        assert s == 200
        assert body["recovered"] is True
        assert body["leases"] == {}
        assert body["max_concurrent"] == 1
        assert body["window"]["max_moves"] == 64


def test_multi_cluster_state_carries_the_fleet_summary(tmp_path):
    snap_a = imbalanced_snapshot(tmp_path, "a.json")
    snap_b = imbalanced_snapshot(tmp_path, "b.json")
    d = AssignerDaemon(
        clusters={"a": snap_a, "b": snap_b}, solver="greedy",
    )
    d.start()
    try:
        s, body, _ = req(d.http_port, "GET", "/state")
        assert s == 200
        assert body["fleet"]["recovered"] is True
        assert body["fleet"]["leases"] == {}
        s, body, _ = req(d.http_port, "GET", "/fleet")
        assert s == 200 and body["recovered"] is True
    finally:
        d.shutdown()
