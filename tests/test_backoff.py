"""The consolidated jittered-backoff progression (``utils/backoff.py``,
ISSUE 8 satellite): the one implementation behind the wire client's connect
passes and session re-establishment and the execution engine's convergence
poll. These tests pin the OBSERVABLE timing contract the three call sites
previously hand-rolled, so the consolidation cannot have drifted it."""
from __future__ import annotations

import random

import pytest

from kafka_assigner_tpu.utils.backoff import JitteredBackoff


def _nominal(base, factor, cap, k):
    n = base * (factor ** (k - 1))
    return n if cap is None else min(n, cap)


def test_stateful_progression_matches_closed_form():
    rng = random.Random(42)
    oracle = random.Random(42)
    b = JitteredBackoff(0.1, cap=2.0, rng=rng)
    for k in range(1, 12):
        want = _nominal(0.1, 2.0, 2.0, k) * (0.5 + oracle.random())
        assert b.next_delay() == pytest.approx(want)


def test_stateless_delay_for_matches_closed_form():
    # The wire client's _reconnect shape: min(0.05 * 2**(k-1), 1.0) * j.
    rng = random.Random(7)
    oracle = random.Random(7)
    b = JitteredBackoff(0.05, cap=1.0, rng=rng)
    for k in (1, 2, 3, 4, 5, 9):
        want = _nominal(0.05, 2.0, 1.0, k) * (0.5 + oracle.random())
        assert b.delay_for(k) == pytest.approx(want)


def test_poll_shape_factor_and_cap():
    # The engine's convergence poll: base=interval, factor 1.5, cap=t/4.
    rng = random.Random(0)
    oracle = random.Random(0)
    b = JitteredBackoff(0.5, factor=1.5, cap=2.5, rng=rng)
    for k in range(1, 10):
        want = _nominal(0.5, 1.5, 2.5, k) * (0.5 + oracle.random())
        assert b.next_delay() == pytest.approx(want)


def test_jitter_bounds():
    b = JitteredBackoff(1.0, cap=1.0)  # nominal pinned at 1.0 throughout
    for _ in range(200):
        d = b.next_delay()
        assert 0.5 <= d < 1.5


def test_peek_nominal_does_not_advance():
    b = JitteredBackoff(0.2, cap=10.0, rng=random.Random(1))
    assert b.peek_nominal() == pytest.approx(0.2)
    assert b.peek_nominal() == pytest.approx(0.2)
    b.next_delay()
    assert b.peek_nominal() == pytest.approx(0.4)


def test_cap_respected_forever():
    b = JitteredBackoff(0.1, cap=0.3, rng=random.Random(3))
    for _ in range(50):
        assert b.next_delay() < 0.3 * 1.5
    assert b.peek_nominal() == pytest.approx(0.3)


def test_invalid_parameters_raise():
    with pytest.raises(ValueError):
        JitteredBackoff(-1.0)
    with pytest.raises(ValueError):
        JitteredBackoff(1.0, factor=0.5)
    with pytest.raises(ValueError):
        JitteredBackoff(1.0).delay_for(0)


def test_seeded_rng_reproduces_schedule():
    a = [JitteredBackoff(0.1, cap=2.0, rng=random.Random(99)).delay_for(k)
         for k in range(1, 6)]
    b = [JitteredBackoff(0.1, cap=2.0, rng=random.Random(99)).delay_for(k)
         for k in range(1, 6)]
    assert a == b
