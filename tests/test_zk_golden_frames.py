"""Pin BOTH jute wire endpoints to spec-derived byte goldens (VERDICT r4
item 3 / missing #1): the in-tree client (``io/zkwire.py``) and the in-tree
test server (``tests/test_zk_socket.py``) were previously only ever tested
against each other, so a shared misunderstanding of the wire format would
have passed every test. ``tests/golden/zk_jute_frames.json`` holds frames
hand-derived field-by-field from Apache ZooKeeper's ``zookeeper.jute``
record definitions (see its ``_derivation`` key) — each side is asserted
byte-for-byte against that third artifact, not against the other side.

Client request bytes are captured with a scripted in-memory socket; server
reply bytes are read off a real TCP connection driven by raw golden frames
(no client code in the loop).
"""
from __future__ import annotations

import json
import pathlib
import socket
import struct

import pytest

from kafka_assigner_tpu.io.zkwire import MiniZkClient

from .test_zk_socket import JuteZkServer

GOLDEN = json.loads(
    (pathlib.Path(__file__).parent / "golden" / "zk_jute_frames.json")
    .read_text()
)


def _g(name: str) -> bytes:
    return bytes.fromhex("".join(GOLDEN[name]["hex"].split()))


class ScriptedSock:
    """Duck-type of the socket surface MiniZkClient uses: records sent
    bytes, replays queued reply frames."""

    def __init__(self, replies):
        self.sent = b""
        self._rx = b"".join(replies)

    def sendall(self, data):
        self.sent += data

    def recv(self, n):
        out, self._rx = self._rx[:n], self._rx[n:]
        return out

    def settimeout(self, t):
        pass

    def close(self):
        pass


def test_client_frames_match_spec_goldens():
    client = MiniZkClient("127.0.0.1:2181", timeout=10.0)
    sock = ScriptedSock(
        [
            _g("connect_response"),
            _g("get_children_response"),
            _g("get_data_response"),
            _g("close_response"),
        ]
    )
    client._sock = sock
    client._handshake(10_000)
    assert sock.sent == _g("connect_request")

    sock.sent = b""
    assert client.get_children("/brokers/ids") == ["1", "2"]
    assert sock.sent == _g("get_children_request")

    sock.sent = b""
    data, stat = client.get("/brokers/ids/1")
    assert data == b"DATA1"
    assert (stat.czxid, stat.dataLength, stat.numChildren) == (1, 5, 0)
    assert sock.sent == _g("get_data_request")

    sock.sent = b""
    client.stop()
    assert sock.sent == _g("close_request")


def test_server_frames_match_spec_goldens():
    server = JuteZkServer(
        {"/brokers/ids/1": b"DATA1", "/brokers/ids/2": b"DATA2"}
    )
    server.start()
    try:
        conn = socket.create_connection(("127.0.0.1", server.port), 5.0)
        conn.settimeout(5.0)

        def roundtrip(frame: bytes) -> bytes:
            conn.sendall(frame)
            head = b""
            while len(head) < 4:
                head += conn.recv(4 - len(head))
            (n,) = struct.unpack(">i", head)
            body = b""
            while len(body) < n:
                body += conn.recv(n - len(body))
            return head + body

        assert roundtrip(_g("connect_request")) == _g("connect_response")
        assert (
            roundtrip(_g("get_children_request"))
            == _g("get_children_response")
        )
        assert roundtrip(_g("get_data_request")) == _g("get_data_response")
        assert roundtrip(_g("close_request")) == _g("close_response")
        conn.close()
    finally:
        server.shutdown()


def test_server_answers_pipelined_requests_in_order():
    """The server side of the pipelining contract: a burst of back-to-back
    requests (the mid-batch-error scenario's frames) is answered with the
    spec-golden replies in request order — ZooKeeper's per-session ordering
    guarantee, which the client's xid matching does not depend on but the
    fixture server must still honor."""
    server = JuteZkServer(
        {"/brokers/ids/1": b"DATA1", "/brokers/ids/2": b"DATA2"}
    )
    server.start()
    try:
        conn = socket.create_connection(("127.0.0.1", server.port), 5.0)
        conn.settimeout(5.0)
        conn.sendall(_g("connect_request"))
        burst = (
            _g("pipelined_get_request_1")
            + _g("pipelined_err_request_2_nope")
            + _g("pipelined_err_request_3")
        )
        expect = (
            _g("connect_response")
            + _g("pipelined_get_response_1")
            + _g("pipelined_err_response_2_nonode")
            + _g("pipelined_err_response_3")
        )
        conn.sendall(burst)
        got = b""
        while len(got) < len(expect):
            chunk = conn.recv(len(expect) - len(got))
            assert chunk, "server closed mid-burst"
            got += chunk
        assert got == expect
        conn.close()
    finally:
        server.shutdown()


def _fresh_client(replies):
    """A handshaken client over a scripted socket preloaded with ``replies``
    (connect_response is prepended; xids then start at 1, exactly like the
    pipelined scenario frames assume)."""
    client = MiniZkClient("127.0.0.1:2181", timeout=10.0)
    sock = ScriptedSock([_g("connect_response")] + list(replies))
    client._sock = sock
    client._handshake(10_000)
    sock.sent = b""
    return client, sock


def test_pipelined_get_many_matches_spec_goldens(monkeypatch):
    """Scenario A: two pipelined gets, replies out of order. Request bytes
    are golden-pinned; decoded results must be byte-identical to serial
    ``get`` calls fed the same (in-order) reply frames."""
    monkeypatch.setenv("KA_ZK_PIPELINE", "8")
    serial_client, _ = _fresh_client(
        [_g("pipelined_get_response_1"), _g("pipelined_get_response_2")]
    )
    serial = [
        serial_client.get("/brokers/ids/1"),
        serial_client.get("/brokers/ids/2"),
    ]
    assert serial[0][0] == b"DATA1" and serial[1][0] == b"DATA2"

    client, sock = _fresh_client(
        # Out-of-order wire arrival: xid2's reply first.
        [_g("pipelined_get_response_2"), _g("pipelined_get_response_1")]
    )
    results = client.get_many(["/brokers/ids/1", "/brokers/ids/2"])
    # Both requests hit the wire back-to-back, before any reply was read.
    assert sock.sent == (
        _g("pipelined_get_request_1") + _g("pipelined_get_request_2")
    )
    assert results == serial  # byte-identical (data, Stat) decode, in order


def test_pipelined_serial_window_is_byte_identical_on_the_wire(monkeypatch):
    """The degradation pin: KA_ZK_PIPELINE=1 produces exactly the serial
    frame sequence — same request bytes, one in flight at a time."""
    monkeypatch.setenv("KA_ZK_PIPELINE", "1")
    client, sock = _fresh_client(
        [_g("pipelined_get_response_1"), _g("pipelined_get_response_2")]
    )
    results = client.get_many(["/brokers/ids/1", "/brokers/ids/2"])
    assert sock.sent == (
        _g("pipelined_get_request_1") + _g("pipelined_get_request_2")
    )
    assert [d for d, _ in results] == [b"DATA1", b"DATA2"]


def test_pipelined_mid_batch_error_xid(monkeypatch):
    """Scenario B: the middle request's reply is a NoNode error xid,
    arriving after a LATER request's reply. The client yields the clean
    prefix (byte-identical to serial), drains the window, and raises at the
    failing position."""
    from kafka_assigner_tpu.io.zkwire import NoNodeError

    monkeypatch.setenv("KA_ZK_PIPELINE", "8")
    serial_client, _ = _fresh_client([_g("pipelined_get_response_1")])
    serial_first = serial_client.get("/brokers/ids/1")

    client, sock = _fresh_client(
        [
            _g("pipelined_err_response_3"),        # later xid lands first
            _g("pipelined_get_response_1"),
            _g("pipelined_err_response_2_nonode"),  # the mid-batch error
        ]
    )
    got = []
    with pytest.raises(NoNodeError, match="/nope"):
        for item in client.iter_get(
            ["/brokers/ids/1", "/nope", "/brokers/ids/2"]
        ):
            got.append(item)
    assert sock.sent == (
        _g("pipelined_get_request_1")
        + _g("pipelined_err_request_2_nope")
        + _g("pipelined_err_request_3")
    )
    assert got == [serial_first]  # the clean prefix, byte-identical


def test_goldens_are_self_consistent():
    """Frame length prefixes inside the golden file itself are coherent —
    a guard against fixture typos (this is how a one-byte miscount in the
    hand derivation was caught)."""
    for name in GOLDEN:
        if name.startswith("_"):
            continue
        raw = _g(name)
        (n,) = struct.unpack(">i", raw[:4])
        assert len(raw) == 4 + n, name