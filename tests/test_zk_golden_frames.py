"""Pin BOTH jute wire endpoints to spec-derived byte goldens (VERDICT r4
item 3 / missing #1): the in-tree client (``io/zkwire.py``) and the in-tree
test server (``tests/test_zk_socket.py``) were previously only ever tested
against each other, so a shared misunderstanding of the wire format would
have passed every test. ``tests/golden/zk_jute_frames.json`` holds frames
hand-derived field-by-field from Apache ZooKeeper's ``zookeeper.jute``
record definitions (see its ``_derivation`` key) — each side is asserted
byte-for-byte against that third artifact, not against the other side.

Client request bytes are captured with a scripted in-memory socket; server
reply bytes are read off a real TCP connection driven by raw golden frames
(no client code in the loop).
"""
from __future__ import annotations

import json
import pathlib
import socket
import struct

from kafka_assigner_tpu.io.zkwire import MiniZkClient

from .test_zk_socket import JuteZkServer

GOLDEN = json.loads(
    (pathlib.Path(__file__).parent / "golden" / "zk_jute_frames.json")
    .read_text()
)


def _g(name: str) -> bytes:
    return bytes.fromhex("".join(GOLDEN[name]["hex"].split()))


class ScriptedSock:
    """Duck-type of the socket surface MiniZkClient uses: records sent
    bytes, replays queued reply frames."""

    def __init__(self, replies):
        self.sent = b""
        self._rx = b"".join(replies)

    def sendall(self, data):
        self.sent += data

    def recv(self, n):
        out, self._rx = self._rx[:n], self._rx[n:]
        return out

    def settimeout(self, t):
        pass

    def close(self):
        pass


def test_client_frames_match_spec_goldens():
    client = MiniZkClient("127.0.0.1:2181", timeout=10.0)
    sock = ScriptedSock(
        [
            _g("connect_response"),
            _g("get_children_response"),
            _g("get_data_response"),
            _g("close_response"),
        ]
    )
    client._sock = sock
    client._handshake(10_000)
    assert sock.sent == _g("connect_request")

    sock.sent = b""
    assert client.get_children("/brokers/ids") == ["1", "2"]
    assert sock.sent == _g("get_children_request")

    sock.sent = b""
    data, stat = client.get("/brokers/ids/1")
    assert data == b"DATA1"
    assert (stat.czxid, stat.dataLength, stat.numChildren) == (1, 5, 0)
    assert sock.sent == _g("get_data_request")

    sock.sent = b""
    client.stop()
    assert sock.sent == _g("close_request")


def test_server_frames_match_spec_goldens():
    server = JuteZkServer(
        {"/brokers/ids/1": b"DATA1", "/brokers/ids/2": b"DATA2"}
    )
    server.start()
    try:
        conn = socket.create_connection(("127.0.0.1", server.port), 5.0)
        conn.settimeout(5.0)

        def roundtrip(frame: bytes) -> bytes:
            conn.sendall(frame)
            head = b""
            while len(head) < 4:
                head += conn.recv(4 - len(head))
            (n,) = struct.unpack(">i", head)
            body = b""
            while len(body) < n:
                body += conn.recv(n - len(body))
            return head + body

        assert roundtrip(_g("connect_request")) == _g("connect_response")
        assert (
            roundtrip(_g("get_children_request"))
            == _g("get_children_response")
        )
        assert roundtrip(_g("get_data_request")) == _g("get_data_response")
        assert roundtrip(_g("close_request")) == _g("close_response")
        conn.close()
    finally:
        server.shutdown()


def test_goldens_are_self_consistent():
    """Frame length prefixes inside the golden file itself are coherent —
    a guard against fixture typos (this is how a one-byte miscount in the
    hand derivation was caught)."""
    for name in GOLDEN:
        if name.startswith("_"):
            continue
        raw = _g(name)
        (n,) = struct.unpack(">i", raw[:4])
        assert len(raw) == 4 + n, name