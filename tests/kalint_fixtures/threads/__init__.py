# fixture mini-package (parsed by kalint, never imported)
