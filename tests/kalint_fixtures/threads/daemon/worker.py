"""Thread-topology fixture (parsed by kalint, never imported): three
spawned entries (a named ``Thread``, a ``Timer``, an executor ``submit``),
one target the resolver CANNOT see (a closure-nested def — no entry), a
consistently ``_lock``-guarded counter with one forgotten-lock read
(KA022), an unguarded cross-thread flag (KA021), and an ``_alock``/
``_block`` acquisition-order inversion (KA023)."""
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self.count = 0
        self.flag = False

    def start(self, pool):
        threading.Thread(target=self._loop, name="loop").start()
        threading.Timer(5.0, self._tick).start()
        pool.submit(self._work)

        def nested():  # unresolvable target: contributes no entry
            return self.count

        threading.Thread(target=nested).start()

    def _loop(self):
        with self._lock:
            self._bump()
        self.flag = True

    def _tick(self):
        with self._lock:
            self.count = 0

    def _work(self):
        self.flag = False
        return self.count

    def _bump(self):
        # only ever called with _lock already held: must-hold inference
        # has to credit the lock here even though no `with` is in sight
        self.count = self.count + 1

    def forward(self):
        with self._alock:
            with self._block:
                return self.flag

    def backward(self):
        with self._block:
            with self._alock:
                return self.flag
