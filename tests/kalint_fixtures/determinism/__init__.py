"""Determinism-taint fixture tree (parsed by kalint, never imported):
every KA024–KA027 source/sanitizer/sink shape the analyzer must judge,
one function per verdict — see each module's docstring for the expected
finding set. The `# kalint: disable=KA005` comments keep the house
json-boundary rule out of the way; they suppress ONLY KA005, so the
determinism findings anchored on the same lines still surface."""
