"""KA026 shapes: filesystem-enumeration order reaching a sink.

Expected: KA026 in ``manifest`` (``os.listdir`` through a list-comp)
and in ``tree_index`` (``Path.rglob`` iterated); ``manifest_clean``
sorts the enumeration before it becomes observable.
"""
import json
import os


def manifest(d):
    names = [p for p in os.listdir(d) if p.endswith(".json")]
    return json.dumps(names)  # kalint: disable=KA005 -- fixture envelope


def manifest_clean(d):
    names = [p for p in sorted(os.listdir(d)) if p.endswith(".json")]
    return json.dumps(names)  # kalint: disable=KA005 -- fixture envelope


def tree_index(root):
    out = []
    for p in root.rglob("*.journal"):
        out.append(str(p))
    return json.dumps(out)  # kalint: disable=KA005 -- fixture envelope
