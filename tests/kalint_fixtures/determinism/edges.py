"""Sanitizer-recognition edge cases: shapes that LOOK sanitized and are
not. Expected: KA024 in ``wrong_axis`` (the ``sorted()`` copies the set,
the set itself is serialized unsorted), KA024 in ``reshuffle``
(``random.shuffle`` undoes the sort), KA024 in ``materialize``
(``list()`` freezes the arbitrary order without imposing one);
``materialize_clean`` shows the discharging counterpart.
"""
import json
import random


def wrong_axis(parts):
    s = {p.split("-")[0] for p in parts}
    vals = sorted(s)
    keys = [k for k in s]
    return json.dumps({"v": vals, "k": keys})  # kalint: disable=KA005 -- fixture envelope


def reshuffle(parts):
    seq = sorted({p for p in parts})
    random.shuffle(seq)
    return json.dumps(seq)  # kalint: disable=KA005 -- fixture envelope


def materialize(parts):
    s = {p for p in parts}
    items = list(s)
    return json.dumps(items)  # kalint: disable=KA005 -- fixture envelope


def materialize_clean(parts):
    s = {p for p in parts}
    items = list(s)
    items.sort()
    return json.dumps(items)  # kalint: disable=KA005 -- fixture envelope
