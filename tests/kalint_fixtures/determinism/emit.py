"""KA024 shapes: set order reaching a serialization sink.

Expected: KA024 in ``report`` (set materialized through a list-comp),
KA024 in ``_payload`` (set-algebra iteration two hops from the sink,
chain ``envelope → _payload``); ``report_clean`` and ``summary_clean``
discharge via ``sorted()`` / order-insensitive consumers.
"""
import json


def report(parts):
    topics = {p.split("-")[0] for p in parts}
    lines = [t for t in topics]
    return json.dumps(lines)  # kalint: disable=KA005 -- fixture envelope


def report_clean(parts):
    topics = {p.split("-")[0] for p in parts}
    return json.dumps(sorted(topics))  # kalint: disable=KA005 -- fixture envelope


def _payload(things):
    out = []
    for t in things | {"seed"}:
        out.append(t)
    return out


def envelope(things):
    body = {"v": _payload(things)}
    return json.dumps(body)  # kalint: disable=KA005 -- fixture envelope


def summary_clean(parts):
    topics = {p.split("-")[0] for p in parts}
    body = {"n": len(topics), "has_a": "a" in topics}
    return json.dumps(body)  # kalint: disable=KA005 -- fixture envelope
