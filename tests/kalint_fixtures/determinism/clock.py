"""KA025 shapes: wall-clock/uuid values flowing into pinned bytes.

Expected: KA025 in ``build`` (undeclared ``"build"`` field) and in
``tag`` (a raw ``uuid.uuid4()`` return from a sink-reaching function);
``build_clean`` lands every read in a declared field (``ts``,
``request_id``) or a monotonic clock, so it stays silent.
"""
import json
import time
import uuid


def build(env):
    env["build"] = time.time()
    return json.dumps(env)  # kalint: disable=KA005 -- fixture envelope


def build_clean(env):
    env["ts"] = round(time.time(), 3)
    env["request_id"] = uuid.uuid4().hex[:16]
    deadline = time.monotonic() + 5.0
    return json.dumps(env), deadline  # kalint: disable=KA005 -- fixture envelope


def tag(env):
    env["color"] = str(uuid.uuid4())
    return json.dumps(env)  # kalint: disable=KA005 -- fixture envelope
