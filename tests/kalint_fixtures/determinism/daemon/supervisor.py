"""KA027 shapes: thread-racy collections drained at a sink. The file
and class names deliberately match an HTTP surface seed so ``handle``/
``state_view`` run as concurrent request threads.

Expected: KA027 in ``handle`` (``self.samples`` view-drained while the
collector thread republishes it, no common lock — note ``sorted()``
would NOT discharge this); ``state_view`` snapshots ``self.guarded``
under the lock its writer holds, so it stays silent.
"""
import json
import threading


class ClusterSupervisor:
    def __init__(self):
        self._mutex = threading.Lock()
        self.samples = {}
        self.guarded = {}

    def start(self):
        threading.Thread(target=self._collect, name="collector").start()

    def _collect(self):
        self.samples = {"x": 1}
        with self._mutex:
            self.guarded = {"x": 1}

    def handle(self):
        body = {k: v for k, v in self.samples.items()}
        return json.dumps(body)  # kalint: disable=KA005 -- fixture envelope

    def state_view(self):
        with self._mutex:
            snap = dict(self.guarded)
        return json.dumps(snap)  # kalint: disable=KA005 -- fixture envelope
