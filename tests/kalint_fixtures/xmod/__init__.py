# seeded cross-module violation (parsed by kalint, never imported)
