"""... host-sync helper in another: the cross-module KA002 the lint gate
test must catch with its full --explain chain."""
import time


def bias():
    return time.time()
