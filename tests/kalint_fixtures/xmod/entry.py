"""Jitted entry in one module ..."""
import jax

from .helper import bias


def solve(x):
    return x + bias()


solve_jit = jax.jit(solve, static_argnames=())
