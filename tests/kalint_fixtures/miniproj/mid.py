"""Hop one of the traced chain."""
from .leaf import sink


def helper():
    return sink()
