"""Jit entry whose traced closure crosses two module boundaries."""
import jax

from .mid import helper


def solve(x):
    return x + helper()


solve_jit = jax.jit(solve)
