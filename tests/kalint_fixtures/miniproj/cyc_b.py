"""Other half of the import cycle."""
from .cyc_a import ping


def pong(n):
    if n <= 0:
        return 1
    return ping(n - 1)
