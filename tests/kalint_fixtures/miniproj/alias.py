"""`from x import y as z` aliasing fixture."""
from .cyc_a import ping as renamed_ping


def caller():
    return renamed_ping(3)
