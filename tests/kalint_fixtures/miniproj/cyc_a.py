"""Import-cycle fixture: a imports b, b imports a."""
from .cyc_b import pong


def ping(n):
    if n <= 0:
        return 0
    return pong(n - 1)
