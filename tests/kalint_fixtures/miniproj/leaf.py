"""Hop two: the host-sync sink (a deliberate KA002)."""
import time


def sink():
    return time.time()
