"""Method-vs-function resolution fixture: `self.report()` must resolve to
the method, bare `report()` to the module function, and a constructed
instance's method call must resolve through the local type."""


def report():
    return "module function"


class Widget:
    def __init__(self):
        self.count = 0

    def report(self):
        return "method"

    def both(self):
        return self.report(), report()


def use_widget():
    w = Widget()
    return w.report()
