"""Topic-vmapped placement (``KA_PLACE_MODE=vmap``) byte parity.

``ops/assignment.py:place_chunked`` batches the single-leg fast wave across
topics and the solver rescues stranded topics through the sequential scan
chain (``solvers/tpu.py:TpuSolver._place``). The contract is byte-identical
output to the default scan mode on every input class — these tests pin it on
the three interesting classes:

- fast-leg-solvable instances (the vmapped leg does all the work),
- exactly-saturated instances (every topic strands; the rescue does all the
  work — the scaled-down giant replace showcase from test_wave_boundaries),
- ragged chunking (chunk ∤ B, chunk > B) and mixed per-topic RF.

Also pins the kernel-level premise the rescue rests on: fast-only placement
really does flag the saturated instance infeasible (if the fast leg ever
learns to solve it, the rescue test above silently stops exercising the
rescue — this canary fails instead).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assigner_tpu.assigner import TopicAssigner
from kafka_assigner_tpu.models.synthetic import rack_striped_cluster
from kafka_assigner_tpu.solvers.tpu import TpuSolver


def _solve(topics, live, rack_map):
    return TopicAssigner(TpuSolver()).generate_assignments(
        topics, live, rack_map, -1
    )


def _expansion_instance():
    """Multi-topic reassignment the fast leg fully solves (replace 4 of 60
    brokers, plenty of slack)."""
    topic_map, _, racks = rack_striped_cluster(
        60, 12, 24, 3, 5, name_fmt="pv-{:03d}", extra_brokers=4
    )
    topics = list(topic_map.items())
    live = set(range(4, 64))
    return topics, live, {b: racks[b] for b in live}


def _saturated_instance():
    """Every topic strands the fast leg (same shape as
    test_wave_boundaries._saturated_instance, split into 4 topics so the
    rescue handles a multi-topic subset)."""
    topic_map, _, racks = rack_striped_cluster(
        50, 4, 250, 3, 5, name_fmt="pvsat-{:02d}", extra_brokers=10
    )
    topics = list(topic_map.items())
    live = set(range(10, 60))
    return topics, live, {b: racks[b] for b in live}


def test_vmap_equals_scan_on_fast_solvable(monkeypatch):
    topics, live, rack_map = _expansion_instance()
    base = _solve(topics, live, rack_map)
    monkeypatch.setenv("KA_PLACE_MODE", "vmap")
    assert _solve(topics, live, rack_map) == base


@pytest.mark.parametrize("chunk", ["1", "5", "64"])
def test_vmap_equals_scan_across_chunk_shapes(monkeypatch, chunk):
    """chunk=1 (degenerate), 5 (ragged: 12 topics -> 3 chunks, 3 inert
    pads), 64 (> B: single full-batch chunk)."""
    topics, live, rack_map = _expansion_instance()
    base = _solve(topics, live, rack_map)
    monkeypatch.setenv("KA_PLACE_MODE", "vmap")
    monkeypatch.setenv("KA_PLACE_CHUNK", chunk)
    assert _solve(topics, live, rack_map) == base


def test_vmap_rescue_on_saturated(monkeypatch):
    """All four topics strand the fast leg; output must still be
    byte-identical to the scan chain (the rescue re-solves them through it)
    with optimal movement."""
    topics, live, rack_map = _saturated_instance()
    base = _solve(topics, live, rack_map)
    monkeypatch.setenv("KA_PLACE_MODE", "vmap")
    got = _solve(topics, live, rack_map)
    assert got == base
    cur = dict(topics)
    moved = sum(
        1
        for t, a in got
        for p, r in a.items()
        for b in r
        if b not in cur[t][p]
    )
    assert moved == 600  # only the replaced brokers' replicas move


def test_fast_only_strands_saturated_canary():
    """Kernel-level premise of the rescue test: fast-only placement flags
    the saturated topics infeasible."""
    from kafka_assigner_tpu.models.problem import encode_topic_group
    from kafka_assigner_tpu.ops.assignment import place_chunked_jit

    topics, live, rack_map = _saturated_instance()
    encs, currents, jhashes, p_reals = encode_topic_group(
        topics, rack_map, live, [3] * len(topics)
    )
    *_, infeasible, _, _ = place_chunked_jit(
        jnp.asarray(currents),
        jnp.asarray(encs[0].rack_idx),
        jnp.asarray(jhashes),
        jnp.asarray(p_reals),
        n=encs[0].n,
        rf=3,
        chunk=8,
        r_cap=encs[0].r_cap,
    )
    assert bool(np.asarray(infeasible)[: len(encs)].all())


def test_vmap_mixed_rf(monkeypatch):
    """Mixed per-topic RF rides the traced rfs lane through the vmapped
    placement."""
    topic_map, _, racks = rack_striped_cluster(
        30, 6, 16, 3, 5, name_fmt="pvrf-{:02d}", extra_brokers=0
    )
    topics = list(topic_map.items())
    live = set(range(30))
    rack_map = {b: racks[b] for b in live}
    rfs = [3, 2, 3, 1, 2, 3]
    base = TpuSolver().assign_many(topics, rack_map, live, rfs)
    monkeypatch.setenv("KA_PLACE_MODE", "vmap")
    assert TpuSolver().assign_many(topics, rack_map, live, rfs) == base


def test_narrow_boundary_values_match_wide():
    """place_scan_narrow returns the same VALUES as place_scan, only
    narrower dtypes (the host-boundary transfer optimization must never
    change a placement)."""
    from kafka_assigner_tpu.models.problem import encode_topic_group
    from kafka_assigner_tpu.ops.assignment import (
        place_scan_jit,
        place_scan_narrow_jit,
    )

    topics, live, rack_map = _expansion_instance()
    encs, currents, jhashes, p_reals = encode_topic_group(
        topics, rack_map, live, [3] * len(topics)
    )
    args = (
        jnp.asarray(currents),
        jnp.asarray(encs[0].rack_idx),
        jnp.asarray(jhashes),
        jnp.asarray(p_reals),
    )
    kw = dict(n=encs[0].n, rf=3, wave_mode="auto", r_cap=encs[0].r_cap)
    wide = jax.device_get(place_scan_jit(*args, **kw))
    narrow = jax.device_get(place_scan_narrow_jit(*args, **kw))
    assert narrow[0].dtype == np.int16
    assert narrow[1].dtype == np.int8
    for w, na in zip(wide, narrow):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(na))
