"""Slow-marked wrapper around ``scripts/bench_warmstart.py`` (ISSUE 6
acceptance): fresh-process warm start — cold compile vs store load across
real process boundaries, ≥5× acquisition speedup asserted by the script
itself, plans byte-identical. Kept out of tier-1 (four child interpreters,
one full cold compile); the fast in-process cycle is the
``scripts/warmstart_smoke.py`` lint-gate smoke."""
from __future__ import annotations

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_warmstart_fresh_process(tmp_path):
    out = tmp_path / "BENCH_warmstart.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_warmstart.py"),
         "--out", str(out)],
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert out.exists()
