"""Hermetic CLI tests against a JSON snapshot backend — the CLI-level coverage
the reference never had (SURVEY.md §4: no integration or CLI tests, untested
ZK layer)."""
from __future__ import annotations

import json

import pytest

from kafka_assigner_tpu.cli import run_tool
from kafka_assigner_tpu.io.json_io import parse_reassignment_json

from .helpers import verify_and_count


@pytest.fixture()
def snapshot(tmp_path):
    """6 brokers across 3 racks, two topics; broker 105 idle on purpose."""
    cluster = {
        "brokers": [
            {"id": 100 + i, "host": f"host{i}", "port": 9092, "rack": f"r{i % 3}"}
            for i in range(6)
        ],
        "topics": {
            "events": {str(p): [100 + (p + i) % 5 for i in range(3)] for p in range(6)},
            "logs": {str(p): [100 + (p + i) % 5 for i in range(2)] for p in range(4)},
        },
    }
    path = tmp_path / "cluster.json"
    path.write_text(json.dumps(cluster))
    return str(path), cluster


def _run(capsys, *argv):
    rc = run_tool(list(argv))
    captured = capsys.readouterr()
    return rc, captured.out, captured.err


def test_usage_errors(capsys, snapshot):
    path, _ = snapshot
    rc, _, err = _run(capsys, "--mode", "PRINT_CURRENT_BROKERS")
    assert rc == 1 and "--zk_string is required" in err
    rc, _, err = _run(capsys, "--zk_string", path)
    assert rc == 1 and "--mode is required" in err
    rc, _, err = _run(
        capsys, "--zk_string", path, "--mode", "PRINT_REASSIGNMENT",
        "--integer_broker_ids", "1", "--broker_hosts", "host1",
    )
    # Correct flag names in the error (the reference cites nonexistent ones).
    assert rc == 1 and "--integer_broker_ids and --broker_hosts" in err


def test_print_current_brokers(capsys, snapshot):
    path, cluster = snapshot
    rc, out, _ = _run(capsys, "--zk_string", path, "--mode", "PRINT_CURRENT_BROKERS")
    assert rc == 0
    header, payload = out.strip().split("\n", 1)
    assert header == "CURRENT BROKERS:"
    entries = json.loads(payload)
    assert [e["id"] for e in entries] == [100, 101, 102, 103, 104, 105]
    assert all(e["rack"] == f"r{(e['id'] - 100) % 3}" for e in entries)


def test_print_current_assignment(capsys, snapshot):
    path, cluster = snapshot
    rc, out, _ = _run(
        capsys, "--zk_string", path, "--mode", "PRINT_CURRENT_ASSIGNMENT"
    )
    assert rc == 0
    header, payload = out.strip().split("\n", 1)
    assert header == "CURRENT ASSIGNMENT:"
    parsed = parse_reassignment_json(payload)
    assert parsed["events"][0] == [100, 101, 102]
    assert parsed["logs"][3] == [103, 104]


def test_print_reassignment_full_pipeline(capsys, snapshot):
    path, cluster = snapshot
    rc, out, _ = _run(capsys, "--zk_string", path, "--mode", "PRINT_REASSIGNMENT")
    assert rc == 0
    # Rollback snapshot precedes the new assignment
    # (KafkaAssignmentGenerator.java:159-160).
    assert out.index("CURRENT ASSIGNMENT:") < out.index("NEW ASSIGNMENT:")
    new_payload = out.split("NEW ASSIGNMENT:\n", 1)[1].strip()
    new = parse_reassignment_json(new_payload)
    current = {
        t: {int(p): r for p, r in parts.items()}
        for t, parts in cluster["topics"].items()
    }
    for topic in current:
        verify_and_count(current[topic], new[topic], 1)


def test_reassignment_excludes_hosts(capsys, snapshot):
    path, cluster = snapshot
    # Rack-disabled: removing host0 leaves rack r0 with a single broker, which
    # is infeasible for RF == #racks (the greedy's hard constraint); this test
    # targets the exclusion plumbing, not solver feasibility.
    rc, out, _ = _run(
        capsys, "--zk_string", path, "--mode", "PRINT_REASSIGNMENT",
        "--broker_hosts_to_remove", "host0", "--disable_rack_awareness",
    )
    assert rc == 0
    new = parse_reassignment_json(out.split("NEW ASSIGNMENT:\n", 1)[1].strip())
    assert all(100 not in r for parts in new.values() for r in parts.values())


def test_reassignment_unknown_include_host_fails(capsys, snapshot):
    path, _ = snapshot
    with pytest.raises(ValueError, match="Some hostnames could not be found"):
        run_tool([
            "--zk_string", path, "--mode", "PRINT_REASSIGNMENT",
            "--broker_hosts", "host0,no-such-host",
        ])


def test_reassignment_topics_filter(capsys, snapshot):
    path, _ = snapshot
    rc, out, _ = _run(
        capsys, "--zk_string", path, "--mode", "PRINT_REASSIGNMENT",
        "--topics", "logs",
    )
    assert rc == 0
    new = parse_reassignment_json(out.split("NEW ASSIGNMENT:\n", 1)[1].strip())
    assert set(new) == {"logs"}


def test_reassignment_rf_override(capsys, snapshot):
    path, cluster = snapshot
    rc, out, _ = _run(
        capsys, "--zk_string", path, "--mode", "PRINT_REASSIGNMENT",
        "--topics", "logs", "--desired_replication_factor", "3",
    )
    assert rc == 0
    new = parse_reassignment_json(out.split("NEW ASSIGNMENT:\n", 1)[1].strip())
    assert all(len(r) == 3 for r in new["logs"].values())


def test_disable_rack_awareness(capsys, snapshot):
    path, _ = snapshot
    rc, out, _ = _run(
        capsys, "--zk_string", path, "--mode", "PRINT_REASSIGNMENT",
        "--disable_rack_awareness",
    )
    assert rc == 0  # solves without rack constraints


def test_integer_broker_ids_restrict_target_set(capsys, snapshot):
    path, cluster = snapshot
    rc, out, _ = _run(
        capsys, "--zk_string", path, "--mode", "PRINT_REASSIGNMENT",
        "--topics", "logs", "--integer_broker_ids", "100,101,102",
        "--disable_rack_awareness",
    )
    assert rc == 0
    new = parse_reassignment_json(out.split("NEW ASSIGNMENT:\n", 1)[1].strip())
    assert set(b for r in new["logs"].values() for b in r) <= {100, 101, 102}


def test_invalid_broker_id(capsys, snapshot):
    path, _ = snapshot
    with pytest.raises(ValueError, match="Invalid broker ID"):
        run_tool([
            "--zk_string", path, "--mode", "PRINT_REASSIGNMENT",
            "--integer_broker_ids", "100,abc",
        ])


def test_native_solver_cli_matches_greedy(capsys, snapshot):
    path, _ = snapshot
    try:
        from kafka_assigner_tpu.solvers.base import get_solver
        get_solver("native")
    except NotImplementedError:
        pytest.skip("no C++ toolchain")
    rc1, out1, _ = _run(capsys, "--zk_string", path, "--mode", "PRINT_REASSIGNMENT",
                        "--solver", "greedy")
    rc2, out2, _ = _run(capsys, "--zk_string", path, "--mode", "PRINT_REASSIGNMENT",
                        "--solver", "native")
    assert rc1 == rc2 == 0
    assert out1 == out2  # byte-identical, including leadership ordering


def test_leadership_context_persists_across_runs(capsys, snapshot, tmp_path):
    # SURVEY.md §5 checkpoint/resume: counters survive process boundaries, so
    # a second run continues balancing instead of restarting from zero.
    path, _ = snapshot
    ctx_file = str(tmp_path / "ctx.json")
    rc, out1, _ = _run(capsys, "--zk_string", path, "--mode", "PRINT_REASSIGNMENT",
                       "--topics", "events", "--leadership_context", ctx_file)
    assert rc == 0
    import json as _json
    saved = _json.load(open(ctx_file))
    assert saved  # counters recorded
    rc, out2, _ = _run(capsys, "--zk_string", path, "--mode", "PRINT_REASSIGNMENT",
                       "--topics", "events", "--leadership_context", ctx_file)
    assert rc == 0
    # Same cluster state -> same replica sets; the persisted counters keep
    # accumulating across processes (the reference's Context dies with the
    # JVM, KafkaAssignmentStrategy.java:360-369).
    new1 = parse_reassignment_json(out1.split("NEW ASSIGNMENT:\n", 1)[1].strip())
    new2 = parse_reassignment_json(out2.split("NEW ASSIGNMENT:\n", 1)[1].strip())
    assert {t: {p: set(r) for p, r in parts.items()} for t, parts in new1.items()} \
        == {t: {p: set(r) for p, r in parts.items()} for t, parts in new2.items()}
    saved2 = _json.load(open(ctx_file))
    total1 = sum(c for slots in saved.values() for c in slots.values())
    total2 = sum(c for slots in saved2.values() for c in slots.values())
    assert total2 == 2 * total1


def test_rank_decommission_mode(capsys, snapshot):
    path, cluster = snapshot
    rc, out, _ = _run(
        capsys, "--zk_string", path, "--mode", "RANK_DECOMMISSION",
        "--disable_rack_awareness",
    )
    assert rc == 0
    header, payload = out.strip().split("\n", 1)
    assert header == "DECOMMISSION RANKING:"
    ranking = json.loads(payload)
    assert {e["broker"] for e in ranking} == {100 + i for i in range(6)}
    moves = [e["moved_replicas"] for e in ranking if e["feasible"]]
    assert moves == sorted(moves)
    # broker 105 holds nothing, so removing it is the least disruptive option
    assert ranking[0]["broker"] == 105 and ranking[0]["moved_replicas"] == 0


def test_print_fresh_assignment_mode(capsys, snapshot):
    path, _ = snapshot
    rc, out, _ = _run(
        capsys, "--zk_string", path, "--mode", "PRINT_FRESH_ASSIGNMENT",
        "--topics", "newtopic", "--partition_count", "8",
        "--desired_replication_factor", "2",
    )
    assert rc == 0
    payload = out.split("FRESH ASSIGNMENT:\n", 1)[1].strip()
    new = parse_reassignment_json(payload)
    assert set(new["newtopic"]) == set(range(8))
    rack = {100 + i: f"r{i % 3}" for i in range(6)}
    for replicas in new["newtopic"].values():
        assert len(replicas) == 2
        assert len({rack[b] for b in replicas}) == 2


def test_fresh_assignment_requires_shape_flags(capsys, snapshot):
    path, _ = snapshot
    rc, _, err = _run(capsys, "--zk_string", path, "--mode", "PRINT_FRESH_ASSIGNMENT")
    assert rc == 1 and "requires --topics" in err


def test_fresh_assignment_honors_exclusions(capsys, snapshot):
    path, _ = snapshot
    rc, out, _ = _run(
        capsys, "--zk_string", path, "--mode", "PRINT_FRESH_ASSIGNMENT",
        "--topics", "newtopic", "--partition_count", "6",
        "--desired_replication_factor", "2",
        "--broker_hosts_to_remove", "host5",
    )
    assert rc == 0
    new = parse_reassignment_json(out.split("FRESH ASSIGNMENT:\n", 1)[1].strip())
    assert all(105 not in r for r in new["newtopic"].values())
    rc, _, err = _run(
        capsys, "--zk_string", path, "--mode", "PRINT_FRESH_ASSIGNMENT",
        "--topics", "t", "--partition_count", "0",
        "--desired_replication_factor", "2",
    )
    assert rc == 1 and "positive --partition_count" in err


def test_rank_decommission_scenario_file(capsys, snapshot, tmp_path):
    # VERDICT r3 item 10: arbitrary removal SETS ranked in one sweep. Mixes
    # integer ids and hostnames; includes the idle broker 105 (0 moves), a
    # pair, and the empty scenario (remove nothing -> 0 moves, trivially
    # feasible).
    path, cluster = snapshot
    scen_path = tmp_path / "scenarios.json"
    scen_path.write_text(json.dumps([[100, 101], ["host5"], [102], []]))
    rc, out, _ = _run(
        capsys, "--zk_string", path, "--mode", "RANK_DECOMMISSION",
        "--disable_rack_awareness", "--scenario_file", str(scen_path),
    )
    assert rc == 0
    header, payload = out.strip().split("\n", 1)
    assert header == "DECOMMISSION RANKING:"
    ranking = json.loads(payload)
    assert [e["brokers"] for e in ranking if e["feasible"]] == sorted(
        [e["brokers"] for e in ranking if e["feasible"]],
        key=lambda b: next(
            e["moved_replicas"] for e in ranking if e["brokers"] == b
        ),
    )
    by_set = {tuple(e["brokers"]): e for e in ranking}
    # Remove-nothing is trivially feasible. (It is NOT guaranteed minimal
    # movement: removing the idle broker 105 RAISES ceil(P*RF/N) for the
    # survivors, which can legalize an otherwise over-capacity layout and
    # move strictly less than the all-brokers rebalance.)
    assert by_set[()]["feasible"]
    assert (105,) in by_set  # "host5" resolved through the live broker list
    assert (100, 101) in by_set and (102,) in by_set
    # A removal set must move at least every replica the removed brokers
    # held (possibly more: capacity ripple on the survivors).
    held = sum(
        1
        for parts in cluster["topics"].values()
        for replicas in parts.values()
        for b in replicas
        if b in (100, 101)
    )
    assert by_set[(100, 101)]["moved_replicas"] >= held > 0


def test_rank_decommission_scenario_file_rejects_unknown(capsys, snapshot, tmp_path):
    # Unknown entries raise (the CLI's reference-style loud failure path)
    # instead of silently ranking a different scenario than asked.
    path, _ = snapshot
    scen_path = tmp_path / "scenarios.json"
    scen_path.write_text(json.dumps([[999]]))
    with pytest.raises(ValueError, match="unknown broker id 999"):
        run_tool([
            "--zk_string", path, "--mode", "RANK_DECOMMISSION",
            "--disable_rack_awareness", "--scenario_file", str(scen_path),
        ])
    scen_path.write_text(json.dumps([["nosuchhost"]]))
    with pytest.raises(ValueError, match="unknown broker host 'nosuchhost'"):
        run_tool([
            "--zk_string", path, "--mode", "RANK_DECOMMISSION",
            "--disable_rack_awareness", "--scenario_file", str(scen_path),
        ])
