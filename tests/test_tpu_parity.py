"""Differential tests: TPU solver vs the greedy oracle.

Contract (solvers/tpu.py header):
- movement parity: the sticky phase reproduces greedy's decisions exactly, so
  the moved-replica count is *identical* (0% extra, vs the ≤1% BASELINE budget);
- leadership parity: given identical replica sets, preference ordering matches
  greedy bit-for-bit (same counter tie-breaks);
- steady state (no orphans): full output equality.
"""
from __future__ import annotations

import pytest

from kafka_assigner_tpu.assigner import TopicAssigner

from .helpers import moved_replicas
from .test_invariants import CASES, make_cluster  # noqa: F401


@pytest.mark.parametrize("case", CASES, ids=[str(c) for c in CASES])
def test_movement_parity_with_greedy(case):
    n_brokers, n_partitions, rf, n_racks, remove, add = case
    for seed in range(2):
        current, live, rack_map = make_cluster(
            seed, n_brokers, n_partitions, rf, n_racks, remove, add
        )
        g = TopicAssigner("greedy").generate_assignment(
            f"topic-{seed}", current, live, rack_map, -1
        )
        t = TopicAssigner("tpu").generate_assignment(
            f"topic-{seed}", current, live, rack_map, -1
        )
        assert moved_replicas(current, g) == moved_replicas(current, t)


def test_steady_state_exact_output_parity():
    # No orphans → sticky keeps everything → identical replica sets → the
    # leadership pass must reproduce greedy's exact preference lists.
    current, live, rack_map = make_cluster(0, 10, 50, 3, 5)
    g = TopicAssigner("greedy").generate_assignment("topic-0", current, live, rack_map, -1)
    t = TopicAssigner("tpu").generate_assignment("topic-0", current, live, rack_map, -1)
    assert g == t


def test_leadership_parity_across_topics():
    # Counter state carries across topics identically in both backends.
    ga, ta = TopicAssigner("greedy"), TopicAssigner("tpu")
    current, live, rack_map = make_cluster(1, 12, 24, 3, 4)
    for name in ("alpha", "beta", "gamma", "delta"):
        g = ga.generate_assignment(name, current, live, rack_map, -1)
        t = ta.generate_assignment(name, current, live, rack_map, -1)
        assert g == t, f"diverged at topic {name}"


def test_infeasible_matches_reference_error():
    current = {0: [10, 11], 1: [11, 10]}
    racks = {10: "a", 11: "a", 12: "a"}
    with pytest.raises(ValueError, match="could not be fully assigned"):
        TopicAssigner("tpu").generate_assignment("t", current, {10, 11, 12}, racks, -1)


def test_failed_solve_does_not_pollute_context():
    a = TopicAssigner("tpu")
    current = {0: [10, 11], 1: [11, 10]}
    racks = {10: "a", 11: "a", 12: "a"}
    with pytest.raises(ValueError):
        a.generate_assignment("t", current, {10, 11, 12}, racks, -1)
    assert a.context.counter == {}

    # and the assigner keeps working afterwards
    ok = a.generate_assignment("t2", {0: [10, 11]}, {10, 11, 12}, {}, -1)
    assert len(ok[0]) == 2


@pytest.mark.parametrize("solver", ["greedy", "tpu"])
def test_empty_string_rack_is_a_real_rack(solver):
    # rack "" is a rack like any other: three brokers sharing it cannot host
    # two replicas of one partition.
    current = {0: [10, 11], 1: [11, 10]}
    racks = {10: "", 11: "", 12: ""}
    with pytest.raises(ValueError, match="could not be fully assigned"):
        TopicAssigner(solver).generate_assignment("t", current, {10, 11, 12}, racks, -1)


@pytest.mark.parametrize("solver", ["greedy", "tpu"])
def test_rackless_node_uses_id_string_as_rack(solver):
    # Reference semantics (KafkaAssignmentStrategy.java:82-86): a rackless
    # node's rack id is its id string, so it collides with a real rack named
    # after that id. Bug-compatible in both backends.
    current = {0: [10, 11]}
    racks = {10: "11"}  # node 11 rackless -> rack "11" too
    with pytest.raises(ValueError, match="could not be fully assigned"):
        TopicAssigner(solver).generate_assignment("t", current, {10, 11}, racks, -1)


def test_batched_equals_serial():
    # assign_many must reproduce the serial per-topic loop exactly, including
    # cross-topic leadership counter evolution.
    current, live, rack_map = make_cluster(2, 12, 24, 3, 4, remove=2)
    topics = {f"topic-{i}": current for i in range(5)}

    serial = TopicAssigner("tpu")
    expected = {
        t: serial.generate_assignment(t, cur, live, rack_map, -1)
        for t, cur in topics.items()
    }

    batched = TopicAssigner("tpu")
    got = dict(batched.generate_assignments(topics, live, rack_map, -1))
    assert got == expected
    assert batched.context.counter == serial.context.counter


def test_batched_equals_greedy_steady_state():
    # Steady state: batched TPU output == greedy reference output, topic after
    # topic (identical replica sets -> identical leadership ordering).
    current, live, rack_map = make_cluster(0, 10, 50, 3, 5)
    topics = {f"t{i}": current for i in range(4)}
    greedy = TopicAssigner("greedy")
    expected = {
        t: greedy.generate_assignment(t, cur, live, rack_map, -1)
        for t, cur in topics.items()
    }
    got = dict(TopicAssigner("tpu").generate_assignments(topics, live, rack_map, -1))
    assert got == expected


def test_batched_mixed_rf_groups():
    # Topics with different RFs split into consecutive same-RF runs.
    c2 = {p: [10 + (p + i) % 4 for i in range(2)] for p in range(8)}
    c3 = {p: [10 + (p + i) % 4 for i in range(3)] for p in range(8)}
    topics = {"a": c2, "b": c2, "c": c3, "d": c2}
    live = {10, 11, 12, 13}
    got = dict(TopicAssigner("tpu").generate_assignments(topics, live, {}, -1))
    assert set(got) == {"a", "b", "c", "d"}
    assert all(len(r) == 2 for r in got["a"].values())
    assert all(len(r) == 3 for r in got["c"].values())


def test_batched_infeasible_raises():
    racks = {10: "a", 11: "a", 12: "a"}
    topics = {"ok": {0: [10]}, "bad": {0: [10, 11], 1: [11, 10]}}
    with pytest.raises(ValueError, match="could not be fully assigned"):
        TopicAssigner("tpu").generate_assignments(topics, {10, 11, 12}, racks, -1)


@pytest.mark.parametrize("solver", ["greedy", "tpu"])
def test_duplicate_topics_solved_per_occurrence(solver):
    # A topic listed twice is solved twice; the second solve sees leadership
    # counters advanced by the first (reference loop semantics,
    # KafkaAssignmentGenerator.java:173-176).
    current = {0: [10, 11, 12]}
    live = {10, 11, 12}
    pairs = TopicAssigner(solver).generate_assignments(
        [("dup", current), ("dup", current)], live, {}, -1
    )
    assert [t for t, _ in pairs] == ["dup", "dup"]
    first, second = pairs[0][1], pairs[1][1]
    # Same replica set, but the leader rotates because counters advanced.
    assert set(first[0]) == set(second[0])
    assert first[0][0] != second[0][0]


from .helpers import native_available as _native_available


@pytest.mark.skipif(not _native_available(), reason="no C++ toolchain")
def test_native_matches_python_greedy():
    # The C++ oracle reproduces the Python oracle exactly (same phases, same
    # tie-breaks) on every practical-envelope config.
    for case in CASES[:5]:
        for seed in range(2):
            current, live, rack_map = make_cluster(seed, *case)
            g = TopicAssigner("greedy").generate_assignment(
                f"topic-{seed}", current, live, rack_map, -1
            )
            n = TopicAssigner("native").generate_assignment(
                f"topic-{seed}", current, live, rack_map, -1
            )
            assert g == n


@pytest.mark.skipif(not _native_available(), reason="no C++ toolchain")
def test_native_assign_many_matches_serial():
    current, live, rack_map = make_cluster(2, 12, 24, 3, 4, remove=2)
    topics = [(f"topic-{i}", current) for i in range(5)]
    serial = TopicAssigner("greedy")
    expected = [
        (t, serial.generate_assignment(t, cur, live, rack_map, -1))
        for t, cur in topics
    ]
    batched = TopicAssigner("native")
    got = batched.generate_assignments(topics, live, rack_map, -1)
    assert got == expected
    assert batched.context.counter == serial.context.counter


@pytest.mark.skipif(not _native_available(), reason="no C++ toolchain")
def test_native_infeasible_raises():
    racks = {10: "a", 11: "a", 12: "a"}
    topics = [("ok", {0: [10]}), ("bad", {0: [10, 11], 1: [11, 10]})]
    with pytest.raises(ValueError, match="could not be fully assigned"):
        TopicAssigner("native").generate_assignments(topics, {10, 11, 12}, racks, -1)


def test_partitions_superset_of_current_assignment():
    from kafka_assigner_tpu.solvers.base import get_solver
    # A partition with no current assignment (newly created) is a fresh row:
    # all replicas orphaned, solved like any other. The vectorized encode
    # fast path must not assume every partition id has a current entry.
    from kafka_assigner_tpu.solvers.base import Context

    solver = get_solver("tpu")
    out = solver.assign(
        "t", {0: [1, 2], 1: [2, 3]}, {}, {1, 2, 3, 4}, {0, 1, 2}, 2, Context()
    )
    assert set(out) == {0, 1, 2}
    assert all(len(r) == 2 for r in out.values())


def test_rf_decrease_clamps_to_uniform_rf():
    # Documented divergence (solvers/tpu.py header): on an RF decrease the
    # TPU solver emits exactly RF replicas per partition, where the reference
    # (and the bug-compatible greedy oracle) can retain more.
    current = {0: [10, 11, 12], 1: [11, 12, 13], 2: [12, 13, 10], 3: [13, 10, 11]}
    brokers = {10, 11, 12, 13}
    new = TopicAssigner("tpu").generate_assignment("test", current, brokers, {}, 2)
    assert all(len(r) == 2 for r in new.values())
    # every partition keeps at least one old replica (stickiness)
    for p, r in new.items():
        assert set(r) & set(current[p])


def test_rf_increase_across_width_bucket():
    # Desired RF far above the historical replica-list width: sticky keeps the
    # old replicas, orphan waves fill the rest, racks stay diverse.
    current = {p: [20 + p % 4, 20 + (p + 1) % 4] for p in range(8)}
    brokers = set(range(20, 30))
    racks = {b: f"r{b % 5}" for b in brokers}
    new = TopicAssigner("tpu").generate_assignment("grow", current, brokers, racks, 5)
    from .helpers import verify_full_invariants

    verify_full_invariants(new, racks, sorted(brokers), 5)
    for p, r in new.items():
        assert set(current[p]) <= set(r)  # pure growth: nothing moved


def test_batched_heterogeneous_topic_sizes():
    # One batched call with very different partition counts: everything pads
    # to the group-wide bucket, padded rows stay inert, and the result equals
    # the serial per-topic loop exactly.
    live = set(range(50, 70))
    racks = {b: f"r{b % 5}" for b in live}
    topics = []
    for name, p_count in (("tiny", 3), ("small", 17), ("large", 120)):
        cur = {p: [50 + (p + i) % 20 for i in range(3)] for p in range(p_count)}
        topics.append((name, cur))

    serial = TopicAssigner("tpu")
    expected = [
        (t, serial.generate_assignment(t, cur, live, racks, -1))
        for t, cur in topics
    ]
    batched = TopicAssigner("tpu")
    got = batched.generate_assignments(topics, live, racks, -1)
    assert got == expected
    assert batched.context.counter == serial.context.counter


def test_oversized_context_counter_refused():
    # ADVICE round 1: the leadership key ``count * m + rot`` shares int32
    # space with the BIG sentinel; a persisted context grown past the key
    # space must be refused at encode time, not silently corrupt ordering.
    from kafka_assigner_tpu.models.problem import context_to_array, encode_problem
    from kafka_assigner_tpu.solvers.base import Context

    ctx = Context()
    ctx.counter[1] = {0: 0x3FFFFFFF // 3}
    enc = encode_problem("t", {0: [1, 2, 3]}, {}, {1, 2, 3}, {0}, 3)
    with pytest.raises(ValueError, match="key space"):
        context_to_array(ctx, enc)


def test_seq_leg_rescues_auction_strand_byte_equal():
    # Found by hypothesis (round 4): cap == 1 with an exactly-tight orphan
    # matching — every simultaneous-auction leg (fast/dense/balance) dead-
    # ends, but the reference's sequential first-fit threads through. The
    # final "seq" leg reproduces assignOrphans verbatim, so the rescue is
    # BYTE-equal to greedy, keeping the strict-superset contract real.
    inter = list(range(100, 115))
    racks = {100 + i: f"r{i % 5}" for i in range(15)}
    racks[115] = "r0"
    live = set(range(101, 116))
    rack_map = {b: racks[b] for b in live}
    current = {
        p: [inter[(5 + p + i) % 15] for i in range(3)] for p in range(5)
    }
    g = TopicAssigner("greedy").generate_assignment(
        "__consumer_offsets", current, live, rack_map, -1
    )
    t = TopicAssigner("tpu").generate_assignment(
        "__consumer_offsets", current, live, rack_map, -1
    )
    assert g == t
