"""Streaming ingest/encode overlap (ISSUE 4 tentpole): the chunked
``GroupEncodeAccumulator`` must be byte-identical to the one-shot
``encode_topic_group`` at every chunk size, ``stream_initial_assignment``
must reproduce ``partition_assignment`` exactly (and hand the solver a
pre-encode only when asked), and producer-side failures must surface on the
orchestration thread like serial fetch failures."""
from __future__ import annotations

import json

import numpy as np
import pytest

from kafka_assigner_tpu.generator import stream_initial_assignment
from kafka_assigner_tpu.io.snapshot import SnapshotBackend
from kafka_assigner_tpu.models.problem import (
    GroupEncodeAccumulator,
    encode_topic_group,
)


def _cluster():
    brokers = set(range(100, 112))
    racks = {b: f"r{b % 3}" for b in sorted(brokers) if b != 111}  # one rackless
    topics = []
    for i in range(9):
        p = 1 + (i * 7) % 13
        topics.append(
            (
                f"topic-{i}",
                {
                    pid: [100 + (pid + r + i) % 12 for r in range(2 + i % 3)]
                    for pid in range(p)
                },
            )
        )
    # One topic with a dead broker and one with ragged replica lists: both
    # encode paths (vectorized + general fill) must stream identically.
    topics.append(("dead-broker", {0: [100, 999], 1: [101, 102]}))
    topics.append(("ragged", {0: [100], 1: [101, 102, 103]}))
    return topics, racks, brokers


@pytest.mark.parametrize("chunk", [1, 3, 4, 64])
def test_accumulator_matches_one_shot_encode(chunk):
    topics, racks, brokers = _cluster()
    rfs = [2 + i % 3 for i in range(len(topics))]
    ref_encs, ref_cur, ref_jh, ref_pr = encode_topic_group(
        topics, racks, brokers, rfs
    )
    acc = GroupEncodeAccumulator(racks, brokers)
    for i in range(0, len(topics), chunk):
        acc.add(topics[i:i + chunk])
    encs, cur, jh, pr = acc.finish()
    assert np.array_equal(cur, ref_cur)
    assert np.array_equal(jh, ref_jh)
    assert np.array_equal(pr, ref_pr)
    assert len(encs) == len(ref_encs)
    for e, r in zip(encs, ref_encs):
        assert e.topic == r.topic
        assert (e.n, e.p, e.n_pad, e.p_pad, e.r_cap) == (
            r.n, r.p, r.n_pad, r.p_pad, r.r_cap
        )
        assert e.jhash == r.jhash
        assert np.array_equal(e.partition_ids, r.partition_ids)
        assert np.array_equal(e.current, r.current)
        assert np.array_equal(e.rack_idx, r.rack_idx)
    assert acc.encode_ms >= 0.0


def test_accumulator_empty_group():
    _, racks, brokers = _cluster()
    encs, cur, jh, pr = GroupEncodeAccumulator(racks, brokers).finish()
    assert encs == []
    assert cur.shape == (1, 8, 2)


@pytest.fixture()
def snapshot(tmp_path):
    topics, racks, brokers = _cluster()
    cluster = {
        "brokers": [
            {"id": b, "host": f"h{b}", "port": 9092,
             **({"rack": racks[b]} if b in racks else {})}
            for b in sorted(brokers)
        ],
        "topics": {
            t: {str(p): r for p, r in parts.items()}
            for t, parts in topics
            if t != "dead-broker"  # snapshots only carry live replicas
        },
    }
    path = tmp_path / "cluster.json"
    path.write_text(json.dumps(cluster))
    return str(path)


def test_stream_matches_partition_assignment(snapshot):
    backend = SnapshotBackend(snapshot)
    names = backend.all_topics()
    ref = backend.partition_assignment(names)
    initial, pre = stream_initial_assignment(backend, names)
    assert initial == ref
    assert pre is None  # no encode requested

    topics, racks, brokers = _cluster()
    initial, pre = stream_initial_assignment(
        backend, names, brokers, racks, want_encode=True
    )
    assert initial == ref
    ref_encs, ref_cur, ref_jh, ref_pr = encode_topic_group(
        [(t, ref[t]) for t in names], racks, brokers, 0
    )
    encs, cur, jh, pr = pre
    assert np.array_equal(cur, ref_cur)
    assert np.array_equal(jh, ref_jh)
    assert np.array_equal(pr, ref_pr)
    assert [e.topic for e in encs] == [e.topic for e in ref_encs]


def test_stream_respects_overlap_kill_switch(snapshot, monkeypatch):
    backend = SnapshotBackend(snapshot)
    names = backend.all_topics()
    monkeypatch.setenv("KA_ZK_OVERLAP", "0")
    _, racks, brokers = _cluster()
    initial, pre = stream_initial_assignment(
        backend, names, brokers, racks, want_encode=True
    )
    assert initial == backend.partition_assignment(names)
    assert pre is None  # strictly sequential fetch-then-encode


def test_stream_falls_back_without_fetch_topics(snapshot):
    # Third-party backends predating fetch_topics keep working untouched.
    backend = SnapshotBackend(snapshot)

    class Legacy:
        partition_assignment = backend.partition_assignment

    names = backend.all_topics()
    initial, pre = stream_initial_assignment(Legacy(), names)
    assert initial == backend.partition_assignment(names)
    assert pre is None


def test_producer_error_reraises_on_consumer_thread(snapshot):
    backend = SnapshotBackend(snapshot)
    with pytest.raises(KeyError, match="no_such_topic"):
        stream_initial_assignment(backend, ["no_such_topic"])


def test_third_party_mixed_rf_solver_without_preencoded_kwarg():
    # A mixed-RF batching backend predating the preencoded parameter must
    # keep working: the kwarg is only forwarded when a preencode exists.
    from kafka_assigner_tpu.assigner import TopicAssigner
    from kafka_assigner_tpu.solvers.greedy import GreedySolver

    class LegacyBatcher(GreedySolver):
        supports_mixed_rf = True

        def assign_many(self, named_currents, rack_assignment, nodes, rfs,
                        context):  # no preencoded kwarg on purpose
            return [
                (t, self.assign(t, cur, rack_assignment, set(nodes),
                                set(cur), rf, context))
                for (t, cur), rf in zip(named_currents, rfs)
            ]

    brokers = set(range(1, 9))
    racks = {b: f"r{b % 4}" for b in brokers}
    assigner = TopicAssigner(LegacyBatcher())
    out = assigner.generate_assignments(
        [("t", {0: [1, 2], 1: [2, 3]})], brokers, racks, -1,
    )
    assert out and out[0][0] == "t"


def test_stale_preencoded_cluster_is_rejected():
    # A preencode reused across a broker-set change must fail loudly, not
    # silently solve against the baked-in stale cluster.
    import pytest as _pytest

    from kafka_assigner_tpu.solvers.tpu import TpuSolver

    topics = [("t", {0: [1, 2], 1: [2, 3]})]
    racks = {1: "a", 2: "b", 3: "c", 4: "a"}
    acc = GroupEncodeAccumulator(racks, {1, 2, 3, 4})
    acc.add(topics)
    pre = acc.finish()
    with _pytest.raises(ValueError, match="different broker set"):
        TpuSolver().assign_many(
            topics, racks, {1, 2, 3}, 2, preencoded=pre  # broker 4 removed
        )


def test_explicit_protocol_subclass_inherits_working_fetch_topics(snapshot):
    # base.py's Protocol body is a real default: an explicit subclass that
    # never heard of fetch_topics still streams correctly (non-pipelined).
    from kafka_assigner_tpu.io.base import MetadataBackend

    inner = SnapshotBackend(snapshot)

    class Subclassed(MetadataBackend):
        def brokers(self):
            return inner.brokers()

        def all_topics(self):
            return inner.all_topics()

        def partition_assignment(self, topics):
            return inner.partition_assignment(topics)

        def close(self):
            pass

    backend = Subclassed()
    names = inner.all_topics()
    assert list(backend.fetch_topics(names)) == list(
        inner.fetch_topics(names)
    )
    initial, pre = stream_initial_assignment(backend, names)
    assert initial == inner.partition_assignment(names)


def test_kazoo_async_window_path(monkeypatch):
    # kazoo is not installed in this image; its fetch path — a sliding
    # window of async handles — is pinned against a duck-typed fake, with
    # the in-flight count asserted never to exceed the knob.
    from kafka_assigner_tpu.io.zk import ZkBackend

    class Handle:
        def __init__(self, owner, path):
            self.owner = owner
            self.path = path

        def get(self, timeout=None):
            self.owner.outstanding -= 1
            return (
                json.dumps(
                    {"partitions": {"0": [1, 2], "1": [2, 3]}}
                ).encode(),
                None,
            )

    class FakeKazoo:
        def __init__(self):
            self.outstanding = 0
            self.max_outstanding = 0

        def get_async(self, path):
            self.outstanding += 1
            self.max_outstanding = max(
                self.max_outstanding, self.outstanding
            )
            return Handle(self, path)

    monkeypatch.setenv("KA_ZK_PIPELINE", "3")
    backend = ZkBackend.__new__(ZkBackend)
    backend._zk = FakeKazoo()
    names = [f"t{i}" for i in range(8)]
    out = list(backend.fetch_topics(names))
    assert [t for t, _ in out] == names
    assert all(parts == {0: [1, 2], 1: [2, 3]} for _, parts in out)
    assert backend._zk.max_outstanding == 3  # the window bound held


def test_duplicate_topics_stream_per_occurrence(snapshot):
    backend = SnapshotBackend(snapshot)
    names = backend.all_topics()[:1] * 3
    topics, racks, brokers = _cluster()
    initial, pre = stream_initial_assignment(
        backend, names, brokers, racks, want_encode=True
    )
    assert list(initial) == names[:1]
    encs, cur, jh, pr = pre
    assert [e.topic for e in encs] == names  # solved per occurrence
