"""The cluster-health observability plane (ISSUE 11): assignment scoring,
movement debt, the traffic/lag backend hook, supervisor gauge publishing,
and the observe-mode /recommendations endpoint — unit layers plus
in-process daemon integration against the jute server."""
from __future__ import annotations

import json

import pytest

from kafka_assigner_tpu import faults
from kafka_assigner_tpu.daemon import AssignerDaemon
from kafka_assigner_tpu.io.base import PartitionTraffic
from kafka_assigner_tpu.io.snapshot import SnapshotBackend
from kafka_assigner_tpu.obs import flight, health
from kafka_assigner_tpu.obs import metrics as metrics_mod

from .jute_server import JuteZkServer
from .test_daemon import req


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    faults.reset()
    metrics_mod.disable_cumulative()
    flight.disable()
    yield
    faults.reset()
    metrics_mod.disable_cumulative()
    flight.disable()


@pytest.fixture(autouse=True)
def _daemon_env(monkeypatch):
    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    monkeypatch.setenv("KA_DAEMON_RESYNC_INTERVAL", "0.5")


def imbalanced_tree():
    """Everything on brokers 1-2 of a four-broker/four-rack cluster:
    predictable skew, zero rack violations, and a provably-improving
    rebalance plan."""
    tree = {}
    for i in range(1, 5):
        tree[f"/brokers/ids/{i}"] = json.dumps(
            {"host": f"h{i}", "port": 9092, "rack": f"r{i}"}
        ).encode()
    tree["/brokers/topics/hot"] = json.dumps(
        {"partitions": {str(p): [1, 2] for p in range(4)}}
    ).encode()
    return tree


# --- score_assignment --------------------------------------------------------

def test_balanced_cluster_scores_zero():
    topics = {"t": {0: [1, 2], 1: [3, 4], 2: [2, 1], 3: [4, 3]}}
    s = health.score_assignment(
        {1, 2, 3, 4}, topics, {1: "ra", 2: "rb", 3: "ra", 4: "rb"}
    )
    assert s.replica_spread == 0
    assert s.replica_stddev == 0.0
    assert s.leader_spread == 0
    assert s.rack_violations == 0
    assert s.score == 0.0
    assert (s.brokers, s.topics, s.partitions, s.replicas) == (4, 1, 4, 8)


def test_skew_scores_spread_and_stddev():
    topics = {"hot": {p: [1, 2] for p in range(4)}}
    s = health.score_assignment(
        {1, 2, 3, 4}, topics, {i: f"r{i}" for i in range(1, 5)}
    )
    # counts 4,4,0,0 -> spread 4, stddev 2; leaders all on 1 -> spread 4
    assert s.replica_spread == 4
    assert s.replica_stddev == 2.0
    assert s.leader_spread == 4
    assert s.score == 4 + 0.5 * 4  # no violations


def test_empty_brokers_count_toward_imbalance():
    s = health.score_assignment({1, 2, 3}, {"t": {0: [1]}}, {})
    assert s.replica_spread == 1
    assert s.brokers == 3


def test_rack_violations_counted_per_partition():
    topics = {"t": {0: [1, 2], 1: [1, 3], 2: [2, 3]}}
    rack = {1: "ra", 2: "ra", 3: "rb"}
    s = health.score_assignment({1, 2, 3}, topics, rack)
    assert s.rack_violations == 1  # only partition 0 doubles rack ra
    # unknown racks never violate (a rackless cluster scores clean)
    s2 = health.score_assignment({1, 2, 3}, topics, {})
    assert s2.rack_violations == 0


def test_stray_replicas_outside_live_set_still_count():
    s = health.score_assignment({1, 2}, {"t": {0: [1, 9]}}, {})
    assert s.brokers == 3  # the stray broker 9 appears in the stats
    assert s.replicas == 2


def test_score_composite_weights_violations_heaviest():
    clean = health.score_assignment(
        {1, 2}, {"t": {0: [1, 2]}}, {1: "ra", 2: "rb"}
    )
    dirty = health.score_assignment(
        {1, 2}, {"t": {0: [1, 2]}}, {1: "ra", 2: "ra"}
    )
    assert dirty.score == clean.score + 10.0


# --- movement_debt -----------------------------------------------------------

def test_movement_debt_identity_is_zero():
    cur = {"t": {0: [1, 2], 1: [2, 3]}}
    assert health.movement_debt(cur, cur) == (0, 0)


def test_movement_debt_reorder_moves_leader_only():
    # Same replica set, different preferred leader: zero data movement,
    # one leadership move.
    assert health.movement_debt(
        {"t": {0: [1, 2]}}, {"t": {0: [2, 1]}}
    ) == (0, 1)


def test_movement_debt_counts_new_placements_and_one_sided_partitions():
    cur = {"t": {0: [1, 2]}, "gone": {0: [5, 6]}}
    new = {"t": {0: [2, 3]}, "fresh": {0: [7]}}
    moves, leaders = health.movement_debt(cur, new)
    # t/0: +3 (1 move); gone/0 vanishes (2); fresh/0 appears (1)
    assert moves == 4
    assert leaders == 3  # t leader 1->2, gone 5->None, fresh None->7


# --- traffic hook ------------------------------------------------------------

def test_synthetic_traffic_deterministic_and_skewed():
    a = health.synthetic_partition_traffic({"events": [0, 1, 2, 3]})
    b = health.synthetic_partition_traffic({"events": [3, 2, 1, 0]})
    assert a == b
    rates = {tr.in_bytes for tr in a["events"].values()}
    assert len(rates) > 1  # skew-shaped, not a constant
    for tr in a["events"].values():
        assert isinstance(tr, PartitionTraffic)
        assert tr.in_bytes > 0 and tr.lag >= 0


def test_snapshot_traffic_section_overrides_synthetic(tmp_path):
    snap = tmp_path / "c.json"
    snap.write_text(json.dumps({
        "brokers": [{"id": 1, "host": "h1", "port": 9092}],
        "topics": {"t": {"0": [1], "1": [1]}},
        "traffic": {"t": {"0": {"in_bytes": 1.5, "out_bytes": 2.5,
                                "lag": 7}}},
    }))
    be = SnapshotBackend(str(snap))
    assert be.supports_traffic()
    tr = be.fetch_partition_traffic({"t": [0, 1]})
    assert tr["t"][0] == PartitionTraffic(1.5, 2.5, 7)
    # partition 1 has no recorded meter: synthetic fallback fills it
    synth = health.synthetic_partition_traffic({"t": [1]})["t"][1]
    assert tr["t"][1] == synth


def test_snapshot_without_traffic_reports_synthetic(tmp_path):
    snap = tmp_path / "c.json"
    snap.write_text(json.dumps({
        "brokers": [{"id": 1, "host": "h1", "port": 9092}],
        "topics": {"t": {"0": [1]}},
    }))
    be = SnapshotBackend(str(snap))
    assert not be.supports_traffic()
    assert be.fetch_partition_traffic({"t": [0]}) \
        == health.synthetic_partition_traffic({"t": [0]})


def test_replace_gauges_swaps_series_atomically():
    cum = metrics_mod.CumulativeMetrics()
    base = {"cluster": "a"}
    cum.replace_gauges(
        "traffic.lag",
        {(("partition", "0"), ("topic", "old")): 5.0}, base,
    )
    # another cluster's series must survive the swap
    cum.replace_gauges(
        "traffic.lag",
        {(("partition", "0"), ("topic", "keep")): 9.0}, {"cluster": "b"},
    )
    cum.replace_gauges(
        "traffic.lag",
        {(("partition", "0"), ("topic", "new")): 6.0}, base,
    )
    series = cum.snapshot()["gauges"]["traffic.lag"]
    labels = {dict(k)["topic"]: v for k, v in series.items()}
    assert labels == {"new": 6.0, "keep": 9.0}


# --- recommendation envelope validator ---------------------------------------

def test_validate_recommendation_flags_missing_and_wrong():
    assert health.validate_recommendation([]) \
        == ["recommendation envelope is not a JSON object"]
    problems = health.validate_recommendation({"schema_version": 99})
    assert any("missing required key" in p for p in problems)
    assert any("schema_version" in p for p in problems)
    assert any("policy" in p for p in problems)


# --- daemon integration ------------------------------------------------------

def test_daemon_health_gauges_and_recommendations(monkeypatch):
    from kafka_assigner_tpu.obs import promtext

    monkeypatch.setenv("KA_HEALTH_MOVE_COST", "1000000")
    server = JuteZkServer(imbalanced_tree())
    server.start()
    d = AssignerDaemon(clusters={"a": f"127.0.0.1:{server.port}"},
                       solver="greedy")
    try:
        d.start()
        port = d.http_port

        # health gauges land per cluster in the scrape
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
        fams = promtext.parse(text)
        spread = fams["ka_health_replica_spread"]["samples"]
        assert [(labels, v) for _n, labels, v in spread] \
            == [({"cluster": "a"}, 4.0)]
        lag_labels = {
            (labels["topic"], labels["partition"])
            for _n, labels, _v in fams["ka_traffic_lag"]["samples"]
        }
        assert ("hot", "0") in lag_labels

        # observe-mode endpoint: valid, byte-stable, flips on move_cost
        s, body, _h = req(port, "GET", "/clusters/a/recommendations")
        assert s == 200
        assert health.validate_recommendation(body) == []
        assert body["verdict"] == "hold"  # knob is sky-high
        assert body["candidate"]["moves_required"] > 0
        assert body["cost_model"]["improvement"] > 0
        s, body2, _h = req(port, "GET", "/clusters/a/recommendations")
        assert body2 == body
        s, low, _h = req(
            port, "GET", "/clusters/a/recommendations?move_cost=0"
        )
        assert low["verdict"] == "recommend"
        assert low["candidate"]["projected"]["replica_spread"] \
            < body["current"]["replica_spread"]

        # bad move_cost is a 400, not a crash
        s, err, _h = req(
            port, "GET", "/clusters/a/recommendations?move_cost=cheap"
        )
        assert s == 400 and "move_cost" in err["error"]

        # multi-cluster bare path: helpful 400 naming the clusters
        s, err, _h = req(port, "GET", "/recommendations")
        assert s == 400 and err["clusters"] == ["a"]

        # flight ring carries the audit trail; no writes ever happened
        s, view, _h = req(port, "GET", "/clusters/a/debug/flight")
        verdicts = [e["verdict"] for e in view["events"]
                    if e["kind"] == "recommendation"]
        assert verdicts == ["hold", "hold", "recommend"]
        assert server.write_ops == {"create": 0, "setData": 0, "delete": 0}

        # movement debt published as a gauge after the evaluations
        cum = metrics_mod.cumulative()
        assert cum is not None
        snap = cum.snapshot()
        assert snap["gauges"]["health.movement_debt"][
            (("cluster", "a"),)
        ] > 0
    finally:
        d.shutdown()
        server.shutdown()


def test_single_cluster_recommendations_and_unsynced_503(tmp_path):
    snap = tmp_path / "c.json"
    snap.write_text(json.dumps({
        "brokers": [
            {"id": i, "host": f"h{i}", "port": 9092, "rack": f"r{i}"}
            for i in range(1, 5)
        ],
        "topics": {"hot": {str(p): [1, 2] for p in range(4)}},
    }))
    d = AssignerDaemon(str(snap), solver="greedy")
    try:
        d.start()
        port = d.http_port
        s, body, _h = req(port, "GET", "/recommendations?move_cost=0")
        assert s == 200
        assert health.validate_recommendation(body) == []
        assert body["cluster"] == "default"
        assert body["verdict"] == "recommend"
        # single-cluster health gauges carry NO cluster label
        cum = metrics_mod.cumulative()
        assert () in cum.snapshot()["gauges"]["health.replica_spread"]
    finally:
        d.shutdown()
    # the snapshot file itself is untouched (observe-only, no persists)
    assert json.loads(snap.read_text())["topics"]["hot"]["0"] == [1, 2]


def test_watch_churn_republishes_health_gauges():
    from kafka_assigner_tpu.io.zkwire import MiniZkClient

    server = JuteZkServer(imbalanced_tree())
    server.start()
    d = AssignerDaemon(clusters={"a": f"127.0.0.1:{server.port}"},
                       solver="greedy")
    try:
        d.start()
        cum = metrics_mod.cumulative()

        def spread():
            return cum.snapshot()["gauges"]["health.replica_spread"][
                (("cluster", "a"),)
            ]

        assert spread() == 4
        w = MiniZkClient(f"127.0.0.1:{server.port}")
        w.start()
        try:
            # counter-skew topic: pile replicas on the empty brokers
            w.create("/brokers/topics/counter",
                     b'{"partitions": {"0": [3, 4], "1": [3, 4], '
                     b'"2": [3, 4], "3": [3, 4]}}')
        finally:
            w.close()
        import time

        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and spread() != 0:
            time.sleep(0.05)
        assert spread() == 0  # 4,4,0,0 + 0,0,4,4 -> balanced
    finally:
        d.shutdown()
        server.shutdown()


def test_recommendations_watchdog_flags_overrun(tmp_path):
    """A recommendation solve that overruns its budget must be visible to
    the same overrun telemetry as every other solve-bearing request."""
    import time

    snap = tmp_path / "c.json"
    snap.write_text(json.dumps({
        "brokers": [
            {"id": i, "host": f"h{i}", "port": 9092, "rack": f"r{i}"}
            for i in range(1, 5)
        ],
        "topics": {"hot": {str(p): [1, 2] for p in range(4)}},
    }))
    d = AssignerDaemon(str(snap), solver="greedy")
    try:
        d.start()
        sup = d.supervisor()
        sup.request_timeout = 0.0  # the live-budget override tests use
        code, body, _h = sup.recommendations({"move_cost": "0"})
        assert code == 200 and body["verdict"] == "recommend"
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline \
                and sup.counters().get("daemon.watchdog_exceeded", 0) < 1:
            time.sleep(0.01)
        assert sup.counters()["daemon.watchdog_exceeded"] >= 1
        rec = flight.recorder()
        assert any(
            e["kind"] == "watchdog" and e["path"] == "/recommendations"
            for e in rec.snapshot()
        )
    finally:
        d.shutdown()


def test_recommendations_shed_when_inflight_full(tmp_path, monkeypatch):
    """The shared admission gate covers /recommendations: with the live
    inflight knob at 1 and the slot held, the endpoint sheds 503."""
    snap = tmp_path / "c.json"
    snap.write_text(json.dumps({
        "brokers": [{"id": 1, "host": "h1", "port": 9092}],
        "topics": {"t": {"0": [1]}},
    }))
    monkeypatch.setenv("KA_DAEMON_MAX_INFLIGHT", "1")
    d = AssignerDaemon(str(snap), solver="greedy")
    try:
        d.start()
        sup = d.supervisor()
        assert sup._gate() is None  # hold the one slot
        try:
            code, body, headers = sup.recommendations({})
            assert code == 503 and body["error"] == "overloaded"
            assert headers["Retry-After"] == "1"
            assert sup.counters()["daemon.requests_shed"] == 1
        finally:
            sup._release()
        code, _body, _h = sup.recommendations({})
        assert code == 200
    finally:
        d.shutdown()
