"""The live telemetry plane (ISSUE 10): cumulative registry exactness under
concurrency, Prometheus exposition round-trips, request-ID correlation,
the flight recorder, the access log, and the profile hooks — unit layers
plus in-process daemon integration against the jute server."""
from __future__ import annotations

import contextlib
import http.client
import io
import json
import math
import threading
import time

import pytest

from kafka_assigner_tpu import faults, obs
from kafka_assigner_tpu.daemon import AssignerDaemon
from kafka_assigner_tpu.obs import flight, promtext
from kafka_assigner_tpu.obs import metrics as metrics_mod
from kafka_assigner_tpu.obs.report import AccessLog

from .jute_server import JuteZkServer, cluster_tree
from .test_daemon import fresh_cli, req, running_daemon


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Every test starts (and leaves) the CLI's disabled state; daemons
    constructed inside re-enable their own fresh plane."""
    faults.reset()
    metrics_mod.disable_cumulative()
    flight.disable()
    yield
    faults.reset()
    metrics_mod.disable_cumulative()
    flight.disable()


@pytest.fixture(autouse=True)
def _daemon_env(monkeypatch):
    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    monkeypatch.setenv("KA_DAEMON_RESYNC_INTERVAL", "0.5")


@pytest.fixture()
def server():
    s = JuteZkServer(cluster_tree())
    s.start()
    yield s
    s.shutdown()


# --- CumulativeMetrics -------------------------------------------------------

def test_cumulative_splits_cluster_label_and_sums():
    cum = metrics_mod.CumulativeMetrics(hist_edges=(1.0, 10.0))
    cum.counter_add("daemon.requests@west", 2)
    cum.counter_add("daemon.requests@west")
    cum.counter_add("daemon.requests@east")
    cum.counter_add("daemon.requests")  # single-cluster: no label
    snap = cum.snapshot()
    by_label = snap["counters"]["daemon.requests"]
    assert by_label[(("cluster", "west"),)] == 3
    assert by_label[(("cluster", "east"),)] == 1
    assert by_label[()] == 1
    assert cum.counter_value("daemon.requests@west") == 3
    assert cum.counter_value(
        "daemon.requests", labels={"cluster": "east"}
    ) == 1


def test_cumulative_labeled_hist_bucketing():
    cum = metrics_mod.CumulativeMetrics(hist_edges=(1.0, 10.0))
    for v in (0.5, 5.0, 50.0):
        cum.hist_observe("daemon.http.request_ms", v,
                         labels={"endpoint": "plan", "cluster": "a"})
    snap = cum.snapshot()
    key = (("cluster", "a"), ("endpoint", "plan"))
    h = snap["hists"]["daemon.http.request_ms"][key]
    assert h["counts"] == [1, 1, 1]
    assert h["count"] == 3 and h["sum"] == 55.5


def test_module_writes_feed_both_run_and_cumulative():
    cum = metrics_mod.enable_cumulative(hist_edges=(1.0,))
    with obs.run_capture() as run:
        obs.counter_add("zk.reads", 3)
        obs.gauge_set("plan.moves", 7)
        obs.hist_observe("zk.op_ms", 0.5)
        with obs.hist_ms("zk.op_ms"):
            pass
    # The per-run capture is untouched by the cumulative plane...
    assert run.counters["zk.reads"] == 3
    assert run.gauges["plan.moves"] == 7
    assert run.hists["zk.op_ms"]["count"] == 2
    # ...and the cumulative registry saw the same writes.
    snap = cum.snapshot()
    assert snap["counters"]["zk.reads"][()] == 3
    assert snap["gauges"]["plan.moves"][()] == 7
    assert snap["hists"]["zk.op_ms"][()]["count"] == 2
    # Writes OUTSIDE any capture still accumulate (the daemon watch loop).
    obs.counter_add("zk.reads", 2)
    assert cum.counter_value("zk.reads") == 5
    assert "zk.reads" not in run.counters or run.counters["zk.reads"] == 3


def test_disabled_state_keeps_noop_singleton():
    assert metrics_mod.cumulative() is None
    from kafka_assigner_tpu.obs import trace as trace_mod

    assert obs.hist_ms("zk.op_ms") is trace_mod.NULL_SPAN
    # hist_ms with cumulative-only (no run capture) records there.
    cum = metrics_mod.enable_cumulative(hist_edges=(1.0,))
    with obs.hist_ms("zk.op_ms"):
        pass
    assert cum.snapshot()["hists"]["zk.op_ms"][()]["count"] == 1


# --- promtext ----------------------------------------------------------------

def _sample_snapshot():
    cum = metrics_mod.CumulativeMetrics(hist_edges=(1.0, 10.0))
    cum.counter_add("daemon.requests@west", 4)
    cum.counter_add("daemon.requests")
    cum.gauge_set("plan.moves", 12)
    for v in (0.5, 5.0, 50.0):
        cum.hist_observe("daemon.http.request_ms", v,
                         labels={"endpoint": "plan", "cluster": "west"})
    return cum.snapshot()


def test_render_parse_round_trip():
    text = promtext.render(
        _sample_snapshot(),
        extra_gauges={"process_uptime_seconds": 1.5},
        info={"tool": "kafka-assignment-generator", "report_schema": "1"},
    )
    fams = promtext.parse(text)
    assert fams["ka_build_info"]["type"] == "gauge"
    [(name, labels, value)] = fams["ka_build_info"]["samples"]
    assert value == 1 and labels["tool"] == "kafka-assignment-generator"
    counters = {
        tuple(sorted(lb.items())): v
        for _, lb, v in fams["ka_daemon_requests_total"]["samples"]
    }
    assert counters[(("cluster", "west"),)] == 4
    assert counters[()] == 1
    assert fams["ka_process_uptime_seconds"]["samples"][0][2] == 1.5
    hist = fams["ka_daemon_http_request_ms"]
    assert hist["type"] == "histogram"
    assert promtext.check_histogram(hist) == []
    # Cumulative bucket semantics: le=1 has 1, le=10 has 2, +Inf has 3.
    buckets = {
        lb["le"]: v for name, lb, v in hist["samples"]
        if name.endswith("_bucket")
    }
    assert buckets == {"1": 1, "10": 2, "+Inf": 3}


def test_parse_rejects_malformed_exposition():
    with pytest.raises(promtext.PromParseError):
        promtext.parse("ka_undeclared_total 3\n")  # no TYPE line
    with pytest.raises(promtext.PromParseError):
        promtext.parse("# TYPE ka_x counter\nka_x not-a-number\n")
    with pytest.raises(promtext.PromParseError):
        promtext.parse("# TYPE ka_x wat\n")
    # label bodies are validated sequentially: a dropped comma or junk
    # BETWEEN labels fails (Prometheus rejects both), not just trailing
    with pytest.raises(promtext.PromParseError):
        promtext.parse('# TYPE ka_x counter\nka_x{a="1"b="2"} 1\n')
    with pytest.raises(promtext.PromParseError):
        promtext.parse('# TYPE ka_x counter\nka_x{a="1" !! b="2"} 1\n')
    # a trailing comma is legal exposition
    fams = promtext.parse('# TYPE ka_x counter\nka_x{a="1",} 1\n')
    assert fams["ka_x"]["samples"][0][1] == {"a": "1"}


def test_check_histogram_flags_missing_le_instead_of_crashing():
    text = (
        "# TYPE ka_h histogram\n"
        'ka_h_bucket{cluster="a"} 5\n'   # no le label at all
        'ka_h_bucket{cluster="a",le="+Inf"} 5\n'
        'ka_h_sum{cluster="a"} 1.0\nka_h_count{cluster="a"} 5\n'
    )
    problems = promtext.check_histogram(promtext.parse(text)["ka_h"])
    assert any("le label" in p for p in problems)


def test_check_histogram_flags_inconsistency():
    text = (
        "# TYPE ka_h histogram\n"
        'ka_h_bucket{le="1"} 5\n'
        'ka_h_bucket{le="10"} 3\n'   # not monotone
        'ka_h_bucket{le="+Inf"} 9\n'
        "ka_h_sum 1.0\nka_h_count 8\n"  # +Inf != count
    )
    problems = promtext.check_histogram(promtext.parse(text)["ka_h"])
    assert any("monotone" in p for p in problems)
    assert any("_count" in p for p in problems)


def test_label_escaping_round_trips():
    cum = metrics_mod.CumulativeMetrics()
    cum.counter_add("daemon.requests", 1,
                    labels={"cluster": 'we"st\\x\nq'})
    text = promtext.render(cum.snapshot())
    fams = promtext.parse(text)
    [(_, labels, value)] = fams["ka_daemon_requests_total"]["samples"]
    assert labels["cluster"] == 'we"st\\x\nq' and value == 1


# --- flight recorder ---------------------------------------------------------

def test_flight_ring_bounds_and_filters(tmp_path):
    rec = flight.FlightRecorder(capacity=3)
    for i in range(5):
        rec.record("watch", "a" if i % 2 else "b", event=f"e{i}")
    assert rec.dropped == 2
    events = rec.snapshot()
    assert [e["event"] for e in events] == ["e2", "e3", "e4"]
    assert all(e["seq"] > 2 for e in events)
    # cluster filter keeps that cluster's (and clusterless) events
    rec.record("daemon", event="draining")
    a_events = rec.snapshot(cluster="a")
    assert {e.get("cluster", "a") for e in a_events} == {"a"}
    assert any(e["kind"] == "daemon" for e in a_events)
    # NDJSON flush
    path = tmp_path / "flight.ndjson"
    assert rec.flush(str(path)) == str(path)
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [e["seq"] for e in lines] == [e["seq"] for e in rec.snapshot()]
    # unwritable path: loud, swallowed
    err = io.StringIO()
    assert rec.flush(str(tmp_path / "no" / "dir.ndjson"), err=err) is None
    assert "flight dump" in err.getvalue()


def test_flight_snapshot_order_pinned_to_seq(tmp_path):
    """``oldest first`` is a contract of /debug/flight and the NDJSON
    flush, not an accident of ring layout: even a rotated ring dumps in
    sequence order."""
    rec = flight.FlightRecorder(capacity=4)
    for i in range(4):
        rec.record("watch", event=f"e{i}")
    rec._events.rotate(2)  # simulate any internal reordering
    assert [e["seq"] for e in rec.snapshot()] == [1, 2, 3, 4]
    assert [e["seq"] for e in rec.view()["events"]] == [1, 2, 3, 4]
    path = tmp_path / "flight.ndjson"
    rec.flush(str(path))
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [e["seq"] for e in lines] == [1, 2, 3, 4]


def test_flight_module_activation(monkeypatch):
    assert flight.recorder() is None
    flight.record("daemon", event="ignored")  # disabled: no-op
    monkeypatch.setenv("KA_OBS_FLIGHT_EVENTS", "2")
    rec = flight.enable()
    assert rec is flight.recorder() and rec.capacity == 2
    monkeypatch.setenv("KA_OBS_FLIGHT_EVENTS", "0")
    assert flight.enable() is None  # 0 disables
    monkeypatch.setenv("KA_OBS_FLIGHT_DUMP", "")
    flight.enable(capacity=4)
    flight.record("daemon", event="x")
    assert flight.flush_to_dump() is None  # no dump path: no-op


# --- access log --------------------------------------------------------------

def test_access_log_file_and_stderr(tmp_path):
    path = tmp_path / "access.ndjson"
    log = AccessLog(str(path))
    log.log(request_id="r1", method="POST", path="/plan", code=200)
    log.log(request_id="r2", method="GET", path="/healthz", code=200)
    log.close()
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [ln["request_id"] for ln in lines] == ["r1", "r2"]
    assert all("ts" in ln for ln in lines)
    # append across "restarts", never clobber
    log2 = AccessLog(str(path))
    log2.log(request_id="r3", method="POST", path="/plan", code=200)
    log2.close()
    assert len(path.read_text().splitlines()) == 3
    # unset path: stderr stream
    err = io.StringIO()
    AccessLog(None, err=err).log(request_id="r4", code=503)
    assert json.loads(err.getvalue())["request_id"] == "r4"
    # unopenable path: loud fallback to stderr, not a crash
    err = io.StringIO()
    bad = AccessLog(str(tmp_path / "no" / "log.ndjson"), err=err)
    assert "access log" in err.getvalue()
    bad.log(request_id="r5", code=200)
    assert '"request_id": "r5"' in err.getvalue()


# --- span annotations --------------------------------------------------------

def test_annotations_stamp_spans_recorded_after():
    with obs.run_capture() as run:
        with obs.span("before"):
            pass
        run.annotate("request_id", "rid-1")
        with obs.span("encode"):
            pass
        from kafka_assigner_tpu.obs.trace import record_span

        record_span("warmup", 1.0)
    by_name = {s["name"]: s for s in run.spans}
    assert "request_id" not in by_name["before"]
    assert by_name["encode"]["request_id"] == "rid-1"
    assert by_name["warmup"]["request_id"] == "rid-1"


def test_cli_report_has_no_annotation_keys(tmp_path, capsys):
    """CLI runs never annotate: the schema-v1 report's span records stay
    byte-identical to PR 9 (no request_id key anywhere)."""
    from kafka_assigner_tpu.cli import run_tool

    cluster = {
        "brokers": [
            {"id": 100 + i, "host": f"h{i}", "port": 9092,
             "rack": f"r{i % 3}"} for i in range(6)
        ],
        "topics": {"events": {
            str(p): [100 + (p + i) % 5 for i in range(3)] for p in range(4)
        }},
    }
    snap = tmp_path / "cluster.json"
    snap.write_text(json.dumps(cluster))
    report_path = tmp_path / "report.json"
    rc = run_tool([
        "--zk_string", f"file://{snap}", "--mode", "PRINT_REASSIGNMENT",
        "--solver", "greedy", "--report-json", str(report_path),
    ])
    capsys.readouterr()
    assert rc == 0
    report = json.loads(report_path.read_text())
    assert all("request_id" not in s for s in report["spans"])


# --- profile hooks -----------------------------------------------------------

def test_profile_disabled_is_refusal_not_crash(monkeypatch):
    from kafka_assigner_tpu.obs import profile

    monkeypatch.delenv("KA_OBS_PROFILE_DIR", raising=False)
    monkeypatch.delenv("KA_PROFILE", raising=False)
    assert profile.profile_dir() is None
    with pytest.raises(RuntimeError, match="KA_OBS_PROFILE_DIR"):
        profile.capture_window(0.1)
    with profile.dispatch_trace():  # zero-overhead no-op
        pass


def test_profile_window_capture_and_busy(monkeypatch, tmp_path):
    from kafka_assigner_tpu.obs import profile

    monkeypatch.setenv("KA_OBS_PROFILE_DIR", str(tmp_path))
    with pytest.raises(ValueError):
        profile.capture_window(float("nan"))
    assert profile.capture_window(0.05) == str(tmp_path)
    assert list(tmp_path.iterdir()), "no trace artifact written"
    # busy: a held profiler lock refuses a second capture AND downgrades
    # the dispatch hook to untraced instead of crashing the solve
    assert profile._PROFILER_LOCK.acquire(blocking=False)
    try:
        with pytest.raises(profile.ProfilerBusy):
            profile.capture_window(0.05)
        with profile.dispatch_trace():
            pass
    finally:
        profile._PROFILER_LOCK.release()


# --- daemon integration ------------------------------------------------------

def test_request_id_correlation_end_to_end(server):
    with running_daemon(server) as d:
        port = d.http_port
        s, body, headers = req(port, "POST", "/plan", {})
        assert s == 200
        rid = body["result"]["request_id"]
        assert rid and headers.get("X-Request-Id") == rid
        assert {sp["request_id"] for sp in body["spans"]} == {rid}
        # client-supplied id wins, echoed everywhere
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/plan", body="{}",
                     headers={"X-Request-Id": "client-rid-7"})
        resp = conn.getresponse()
        body2 = json.loads(resp.read())
        assert resp.getheader("X-Request-Id") == "client-rid-7"
        conn.close()
        assert body2["result"]["request_id"] == "client-rid-7"
        assert all(
            sp["request_id"] == "client-rid-7" for sp in body2["spans"]
        )
        # hostile header (control chars) is replaced, not propagated
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/plan", body="{}",
                     headers={"X-Request-Id": "evil\tid"})
        resp = conn.getresponse()
        body3 = json.loads(resp.read())
        conn.close()
        assert body3["result"]["request_id"] != "evil\tid"
        # GET probes carry the header too
        s, _, h = req(port, "GET", "/healthz")
        assert h.get("X-Request-Id")


def test_metrics_endpoint_serves_valid_exposition(server):
    with running_daemon(server) as d:
        port = d.http_port
        s, _, _ = req(port, "POST", "/plan", {})
        assert s == 200
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/plain")
        text = resp.read().decode("utf-8")
        conn.close()
        fams = promtext.parse(text)
        assert "ka_build_info" in fams
        assert "ka_process_start_time_seconds" in fams
        assert "ka_daemon_requests_total" in fams
        for fam, data in fams.items():
            if data["type"] == "histogram":
                assert promtext.check_histogram(data) == [], fam
        # the routing layer's per-endpoint-per-cluster latency histogram
        hist = fams["ka_daemon_http_request_ms"]
        assert any(
            lb.get("endpoint") == "plan" and lb.get("cluster") == "default"
            for _, lb, _ in hist["samples"]
        )


def test_debug_flight_global_and_per_cluster(server):
    with running_daemon(server) as d:
        port = d.http_port
        s, _, _ = req(port, "POST", "/plan", {})
        # The "request" flight event is recorded AFTER the /plan response
        # bytes flush, so an immediately-following /debug/flight can win
        # that race — poll with a bounded deadline for the write to land.
        deadline = time.monotonic() + 5
        while True:
            s, view, _ = req(port, "GET", "/debug/flight")
            assert s == 200
            kinds = {e["kind"] for e in view["events"]}
            if ({"daemon", "lifecycle", "resync", "request"} <= kinds
                    or time.monotonic() >= deadline):
                break
            time.sleep(0.01)
        assert {"daemon", "lifecycle", "resync", "request"} <= kinds
        assert view["dropped"] == 0
        s, per, _ = req(port, "GET", "/clusters/default/debug/flight")
        assert s == 200
        assert all(
            e.get("cluster", "default") == "default" for e in per["events"]
        )
        # request summaries carry the envelope's request id (same bounded
        # poll: the summary lands after the response flush)
        s, body, _ = req(port, "POST", "/plan", {})
        rid = body["result"]["request_id"]

        def _rid_recorded():
            s, view, _ = req(port, "GET", "/debug/flight")
            return any(
                e["kind"] == "request" and e.get("request_id") == rid
                for e in view["events"]
            )

        deadline = time.monotonic() + 5
        while not _rid_recorded() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert _rid_recorded()


def test_stderr_summary_gated_on_ka_obs_report(server, monkeypatch):
    """ISSUE 10 satellite: by default a daemon request emits NO obs stderr
    summary (the access log line is the one structured line); setting
    KA_OBS_REPORT opts the per-request summary back in."""
    err = io.StringIO()
    d = AssignerDaemon(f"127.0.0.1:{server.port}", solver="greedy",
                       err=err)
    d.start()
    try:
        s, _, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200
        assert "obs: run" not in err.getvalue()
        # exactly one access-log line for the one POST (GET probes aside).
        # The line is written by the handler thread AFTER the response
        # bytes flush, so give the post-reply write a bounded moment to
        # land (same race as the lifetime-metrics test above).
        def _plan_lines():
            return [
                ln for ln in err.getvalue().splitlines()
                if ln.startswith("{") and '"path": "/plan"' in ln
            ]

        deadline = time.monotonic() + 5
        while not _plan_lines() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(_plan_lines()) == 1
        monkeypatch.setenv("KA_OBS_REPORT", "/dev/null")
        s, _, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200
        assert "obs: run" in err.getvalue()
    finally:
        monkeypatch.delenv("KA_OBS_REPORT", raising=False)
        d.shutdown()


def test_debug_profile_endpoint(server, monkeypatch, tmp_path):
    with running_daemon(server) as d:
        port = d.http_port
        s, body, _ = req(port, "GET", "/debug/profile?seconds=0.05")
        assert s == 400 and "KA_OBS_PROFILE_DIR" in body["error"]
        monkeypatch.setenv("KA_OBS_PROFILE_DIR", str(tmp_path))
        s, body, _ = req(port, "GET", "/debug/profile?seconds=0.05")
        assert s == 200 and body["artifact_dir"] == str(tmp_path)
        assert list(tmp_path.iterdir())
        s, body, _ = req(port, "GET", "/debug/profile?seconds=wat")
        assert s == 400


# --- the concurrency acceptance: exact sums, no cross-talk -------------------

def test_concurrent_hammer_cumulative_sums_exact():
    """ISSUE 10 satellite: N parallel /plan + /whatif requests across TWO
    clusters — the cumulative registry sums exactly (no lost updates), the
    @cluster labels never cross-talk, and every per-run envelope stays
    byte-identical to a fresh CLI run with per-request (not cumulative)
    counters."""
    sa, sb = JuteZkServer(cluster_tree()), JuteZkServer(cluster_tree())
    sa.start(), sb.start()
    d = None
    try:
        base_a = fresh_cli(sa.port, "--solver", "greedy")
        base_b = fresh_cli(sb.port, "--solver", "greedy")
        d = AssignerDaemon(
            clusters={"a": f"127.0.0.1:{sa.port}",
                      "b": f"127.0.0.1:{sb.port}"},
            solver="greedy", err=io.StringIO(),
        )
        d.start()
        port = d.http_port
        n_threads, per_thread = 4, 3
        failures = []

        def hammer(cluster, base):
            for _ in range(per_thread):
                try:
                    s, body, _ = req(
                        port, "POST", f"/clusters/{cluster}/plan", {}
                    )
                    if s != 200 or body["result"]["stdout"] != base:
                        failures.append(f"{cluster}: http={s}")
                        continue
                    # per-run envelope: THIS request's capture only —
                    # never another cluster's metrics (label cross-talk)
                    # and never cumulative-scale totals
                    c = body["metrics"]["counters"]
                    other = "b" if cluster == "a" else "a"
                    if any(k.endswith(f"@{other}") for k in c):
                        failures.append(f"{cluster}: cross-talk in {c}")
                    if any(v > per_thread for k, v in c.items()
                           if k.startswith("daemon.")):
                        failures.append(
                            f"{cluster}: cumulative totals leaked into "
                            f"the envelope {c}"
                        )
                    s, body, _ = req(
                        port, "POST", f"/clusters/{cluster}/whatif", {}
                    )
                    if s != 200:
                        failures.append(f"{cluster}: whatif http={s}")
                except Exception as e:  # noqa: BLE001 -- collected, asserted below
                    failures.append(f"{cluster}: {type(e).__name__}: {e}")

        threads = [
            threading.Thread(target=hammer,
                             args=(("a", base_a) if i % 2 == 0
                                   else ("b", base_b)))
            for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "hammer thread hung"
        assert failures == [], failures
        cum = metrics_mod.cumulative()
        assert cum is not None
        sent_per_cluster = (n_threads // 2) * per_thread * 2  # plan+whatif
        assert cum.counter_value(
            "daemon.requests", labels={"cluster": "a"}
        ) == sent_per_cluster
        assert cum.counter_value(
            "daemon.requests", labels={"cluster": "b"}
        ) == sent_per_cluster
        # the routing layer's labeled http counters agree exactly
        assert cum.counter_value(
            "daemon.http.requests",
            labels={"endpoint": "plan", "cluster": "a", "code": "200"},
        ) == (n_threads // 2) * per_thread
        assert cum.counter_value(
            "daemon.http.requests",
            labels={"endpoint": "whatif", "cluster": "b", "code": "200"},
        ) == (n_threads // 2) * per_thread
    finally:
        if d is not None:
            d.shutdown()
        sa.shutdown(), sb.shutdown()


def test_daemon_lifetime_metrics_survive_requests(server):
    """Cumulative totals keep growing across requests while each envelope
    stays per-request — the 'process-lifetime vs run capture' split."""
    with running_daemon(server) as d:
        port = d.http_port
        for i in range(3):
            s, body, _ = req(port, "POST", "/plan", {})
            assert s == 200
            # The envelope is the per-REQUEST capture: lifetime totals
            # (admission counters, resyncs) live in the cumulative
            # registry and /state, never in a response's own report.
            assert "daemon.requests" not in body["metrics"]["counters"]
        cum = metrics_mod.cumulative()
        assert cum.counter_value("daemon.requests") == 3
        # time flows only forward in the http latency histogram. The
        # routing layer observes it AFTER the response bytes go out (the
        # latency covers the whole request), so wait out that last write
        # instead of racing it.
        key = (("cluster", "default"), ("endpoint", "plan"))

        def hist_count():
            snap = cum.snapshot()
            return snap["hists"]["daemon.http.request_ms"][key]["count"]

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and hist_count() < 3:
            time.sleep(0.01)
        assert hist_count() == 3


# --- promtext edge cases (ISSUE 11 satellite) --------------------------------

def test_empty_registry_scrape_round_trips():
    """A daemon scraped before any traffic: the exposition of an empty
    registry must still be valid (and parse to no families), not a
    zero-length body some scrapers treat as an outage."""
    empty = {"counters": {}, "gauges": {}, "hists": {}}
    text = promtext.render(empty)
    assert text == "\n"
    assert promtext.parse(text) == {}
    # with only the process gauges (what a freshly-started daemon serves)
    text = promtext.render(empty, extra_gauges={"daemon_clusters": 2},
                           info={"tool": "x"})
    fams = promtext.parse(text)
    assert set(fams) == {"ka_build_info", "ka_daemon_clusters"}


def test_histogram_with_zero_observations_is_consistent():
    """A histogram family whose series never observed anything: all-zero
    cumulative buckets, +Inf == _count == 0, _sum == 0 — consistent, not a
    divide-by-zero or a missing-bucket finding."""
    cum = metrics_mod.CumulativeMetrics(hist_edges=(1.0, 10.0))
    cum.hist_observe("exec.wave_ms", 5.0)  # force the dict entry...
    snap = cum.snapshot()
    h = snap["hists"]["exec.wave_ms"][()]
    h["counts"] = [0] * len(h["counts"])  # ...then zero it out
    h["count"] = 0
    h["sum"] = 0.0
    text = promtext.render(snap)
    fam = promtext.parse(text)["ka_exec_wave_ms"]
    assert promtext.check_histogram(fam) == []
    buckets = {lb["le"]: v for n, lb, v in fam["samples"]
               if n.endswith("_bucket")}
    assert buckets == {"1": 0, "10": 0, "+Inf": 0}


def test_escaped_label_values_round_trip_hard_cases():
    """Label values that LOOK like escape sequences must survive the
    render->parse round trip byte-exactly: literal backslash-n (not a
    newline), quote-backslash runs, and a real newline next to them."""
    cases = ["a\\nb", 'q"\\"w', "line1\nline2\\", "\\\\", "plain"]
    cum = metrics_mod.CumulativeMetrics()
    for i, v in enumerate(cases):
        cum.counter_add("daemon.requests", i + 1, labels={"cluster": v})
    fams = promtext.parse(promtext.render(cum.snapshot()))
    got = {lb["cluster"]: v
           for _n, lb, v in fams["ka_daemon_requests_total"]["samples"]}
    assert got == {v: i + 1.0 for i, v in enumerate(cases)}


def test_scrape_raced_against_sigterm_drain(server):
    """/metrics hammered while another thread drains the daemon: every
    response that arrives must be a complete, parseable exposition with
    consistent histograms — never a torn half-render — and refused
    connections after the drain are the only acceptable failure."""
    d = AssignerDaemon(f"127.0.0.1:{server.port}", solver="greedy")
    d.start()
    port = d.http_port
    s, _body, _h = req(port, "POST", "/plan", {})
    assert s == 200
    results = {"scrapes": 0, "torn": []}
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=5
                )
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                raw = resp.read()
                conn.close()
            except OSError:
                break  # the listener is gone: the race is over
            if resp.status != 200:
                continue
            try:
                fams = promtext.parse(raw.decode("utf-8"))
                for fam, data in fams.items():
                    if data["type"] == "histogram":
                        assert promtext.check_histogram(data) == [], fam
            except (promtext.PromParseError, AssertionError) as e:
                results["torn"].append(str(e))
                break
            results["scrapes"] += 1

    threads = [threading.Thread(target=scraper) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.05)  # let the scrapers land a few pre-drain rounds
    d.shutdown()
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert results["torn"] == []
    assert results["scrapes"] > 0


# --- access-log rollover (ISSUE 11 satellite) --------------------------------

def test_access_log_rollover_caps_size(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_OBS_ACCESS_LOG_MAX_MB", "1")
    path = tmp_path / "access.ndjson"
    log = AccessLog(str(path))
    filler = "x" * 4096
    lines_to_fill = (1024 * 1024) // 4096 + 2
    for i in range(lines_to_fill):
        log.log(request_id=f"r{i}", pad=filler)
    # the cap tripped: current file restarted, .1 holds the old bytes
    rolled = tmp_path / "access.ndjson.1"
    assert rolled.exists()
    assert rolled.stat().st_size >= 1024 * 1024
    assert path.stat().st_size < 1024 * 1024
    # every line is intact on one side of the boundary or the other
    all_lines = (rolled.read_text() + path.read_text()).splitlines()
    ids = [json.loads(ln)["request_id"] for ln in all_lines]
    assert ids == [f"r{i}" for i in range(lines_to_fill)]
    # a second rollover REPLACES .1 (bounded at ~2x the cap, never 3x)
    first_rolled_head = rolled.read_text().splitlines()[0]
    for i in range(lines_to_fill):
        log.log(request_id=f"s{i}", pad=filler)
    log.close()
    assert rolled.read_text().splitlines()[0] != first_rolled_head
    assert not (tmp_path / "access.ndjson.2").exists()


def test_access_log_unbounded_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("KA_OBS_ACCESS_LOG_MAX_MB", raising=False)
    path = tmp_path / "access.ndjson"
    log = AccessLog(str(path))
    for i in range(50):
        log.log(request_id=f"r{i}", pad="y" * 1000)
    log.close()
    assert not (tmp_path / "access.ndjson.1").exists()
    assert len(path.read_text().splitlines()) == 50


def test_access_log_rollover_resumes_count_across_restart(
    tmp_path, monkeypatch
):
    """A restarted daemon opens the log in append mode: the cap must count
    the EXISTING bytes, not restart from zero and overshoot 2x."""
    monkeypatch.setenv("KA_OBS_ACCESS_LOG_MAX_MB", "1")
    path = tmp_path / "access.ndjson"
    filler = "z" * 4096
    log = AccessLog(str(path))
    for i in range(100):  # ~400 KB, under the cap
        log.log(request_id=f"a{i}", pad=filler)
    log.close()
    log2 = AccessLog(str(path))  # restart
    n = 0
    while not (tmp_path / "access.ndjson.1").exists():
        log2.log(request_id=f"b{n}", pad=filler)
        n += 1
        assert n < 400, "rollover never tripped after restart"
    log2.close()
    # tripped well before a full fresh 1 MB of post-restart lines
    assert n < 200


def test_access_log_rollover_failure_reported_once(tmp_path, monkeypatch):
    """A persistently failing rollover (unrenameable .1 target) must warn
    ONCE and disable further attempts — never a stderr line plus a
    close/reopen per served request — while appending keeps working."""
    monkeypatch.setenv("KA_OBS_ACCESS_LOG_MAX_MB", "1")
    path = tmp_path / "access.ndjson"
    (tmp_path / "access.ndjson.1").mkdir()  # os.replace onto a dir fails
    err = io.StringIO()
    log = AccessLog(str(path), err=err)
    filler = "x" * 4096
    n = (1024 * 1024) // 4096 + 10
    for i in range(n):
        log.log(request_id=f"r{i}", pad=filler)
    log.close()
    assert err.getvalue().count("rollover failed") == 1
    assert err.getvalue().count("rollover disabled") == 1
    # every line still landed in the (now over-cap) primary file
    assert len(path.read_text().splitlines()) == n
