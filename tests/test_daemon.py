"""The resident assigner daemon (ISSUE 8): watch-fed cache, incremental
re-encode, the HTTP surface, the supervised lifecycle, and the zkwire watch
protocol underneath it — all against the in-repo jute server (real TCP) or
the hermetic snapshot backend."""
from __future__ import annotations

import contextlib
import http.client
import io
import json
import time

import pytest

from kafka_assigner_tpu import faults
from kafka_assigner_tpu.cli import run
from kafka_assigner_tpu.daemon import AssignerDaemon, CacheBackend, DaemonState
from kafka_assigner_tpu.io.base import BrokerInfo
from kafka_assigner_tpu.io.zkwire import (
    EVENT_CHILDREN_CHANGED,
    EVENT_DATA_CHANGED,
    EVENT_DELETED,
    MiniZkClient,
)
from kafka_assigner_tpu.obs.report import validate_report

from .jute_server import JuteZkServer, cluster_tree


@pytest.fixture(autouse=True)
def _fresh_injector():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(autouse=True)
def _daemon_env(monkeypatch):
    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    monkeypatch.setenv("KA_DAEMON_RESYNC_INTERVAL", "0.5")


@pytest.fixture()
def server():
    s = JuteZkServer(cluster_tree())
    s.start()
    yield s
    s.shutdown()


@contextlib.contextmanager
def running_daemon(server, **kwargs):
    kwargs.setdefault("solver", "greedy")
    d = AssignerDaemon(f"127.0.0.1:{server.port}", **kwargs)
    d.start()
    try:
        yield d
    finally:
        d.shutdown()


def fresh_cli(port_or_path, *extra):
    """A fresh in-process CLI mode-3 run — the byte-identity oracle."""
    zk = (
        port_or_path if isinstance(port_or_path, str)
        else f"127.0.0.1:{port_or_path}"
    )
    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = run(["--zk_string", zk, "--mode", "PRINT_REASSIGNMENT",
                  *extra])
    assert rc == 0, err.getvalue()
    return out.getvalue()


def req(port, method, path, payload=None, timeout=60.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        body = None if payload is None else json.dumps(payload)
        conn.request(method, path, body=body)
        resp = conn.getresponse()
        data = json.loads(resp.read())
        return resp.status, data, dict(resp.getheaders())
    finally:
        conn.close()


# --- zkwire watch protocol ---------------------------------------------------

def test_wire_data_watch_fires_on_set_and_delete(server):
    c = MiniZkClient(f"127.0.0.1:{server.port}")
    c.start()
    w = MiniZkClient(f"127.0.0.1:{server.port}")
    w.start()
    try:
        c.get("/brokers/topics/events", watch=True)
        w.set_data("/brokers/topics/events", b'{"partitions": {"0": [1]}}')
        events = c.poll_watches(timeout=5.0)
        assert [(e.type, e.path) for e in events] == [
            (EVENT_DATA_CHANGED, "/brokers/topics/events")
        ]
        # One-shot: a second mutation without re-arming fires nothing.
        w.set_data("/brokers/topics/events", b'{"partitions": {"0": [2]}}')
        assert c.poll_watches(timeout=0.3) == []
        # Re-arm, then delete → NodeDeleted.
        c.get("/brokers/topics/events", watch=True)
        w.delete("/brokers/topics/events")
        events = c.poll_watches(timeout=5.0)
        assert [(e.type, e.path) for e in events] == [
            (EVENT_DELETED, "/brokers/topics/events")
        ]
    finally:
        c.close()
        w.close()


def test_wire_child_watch_fires_on_create(server):
    c = MiniZkClient(f"127.0.0.1:{server.port}")
    c.start()
    w = MiniZkClient(f"127.0.0.1:{server.port}")
    w.start()
    try:
        kids = c.get_children("/brokers/topics", watch=True)
        assert kids == ["events", "logs"]
        w.create("/brokers/topics/zzz", b'{"partitions": {"0": [1, 2]}}')
        events = c.poll_watches(timeout=5.0)
        assert (EVENT_CHILDREN_CHANGED, "/brokers/topics") in [
            (e.type, e.path) for e in events
        ]
    finally:
        c.close()
        w.close()


def test_wire_watch_notification_between_replies_is_queued(server):
    """A notification landing while a normal read is in flight must not
    desync the xid matching: it queues for poll_watches."""
    c = MiniZkClient(f"127.0.0.1:{server.port}")
    c.start()
    w = MiniZkClient(f"127.0.0.1:{server.port}")
    w.start()
    try:
        c.get("/brokers/topics/logs", watch=True)
        w.set_data("/brokers/topics/logs", b'{"partitions": {"0": [3]}}')
        time.sleep(0.2)  # let the notification hit c's socket buffer
        data, _ = c.get("/brokers/topics/events")  # normal read still works
        assert b"partitions" in data
        events = c.poll_watches(timeout=1.0)
        assert [e.path for e in events] == ["/brokers/topics/logs"]
    finally:
        c.close()
        w.close()


def test_session_generation_bumps_on_restart(server):
    c = MiniZkClient(f"127.0.0.1:{server.port}")
    c.start()
    try:
        g0 = c.session_generation
        assert g0 >= 1
        c.stop()
        c.close()
        c.start()
        assert c.session_generation == g0 + 1
    finally:
        c.close()


# --- DaemonState / CacheBackend ---------------------------------------------

def _state_fixture():
    st = DaemonState()
    brokers = [
        BrokerInfo(id=i, host=f"h{i}", port=9092, rack=f"r{i % 2}")
        for i in range(1, 5)
    ]
    st.reset(brokers, {
        "events": {0: [1, 2], 1: [2, 3]},
        "logs": {0: [3, 4]},
    })
    return st


def test_cache_backend_serves_metadata():
    st = _state_fixture()
    be = CacheBackend(st)
    assert [b.id for b in be.brokers()] == [1, 2, 3, 4]
    assert be.all_topics() == ["events", "logs"]
    assert be.partition_assignment(["logs"]) == {"logs": {0: [3, 4]}}
    assert list(be.fetch_topics(["logs", "ghost"], missing="skip")) == [
        ("logs", {0: [3, 4]}), ("ghost", None),
    ]
    with pytest.raises(KeyError):
        be.partition_assignment(["ghost"])


def test_state_delta_and_plan_inputs():
    st = _state_fixture()
    v0 = st.version
    assert st.apply_topic("fresh", {0: [1, 2, 3]})
    assert st.version == v0 + 1
    initial, pre = st.plan_inputs(["events", "fresh"], want_encode=True)
    assert initial["fresh"] == {0: [1, 2, 3]}
    encs, currents, jh, pr = pre
    assert [e.topic for e in encs] == ["events", "fresh"]
    # delete
    assert not st.apply_topic("fresh", None)
    with pytest.raises(KeyError):
        st.plan_inputs(["fresh"], want_encode=False)


# --- the HTTP surface --------------------------------------------------------

def test_endpoints_and_plan_byte_identity(server):
    base = fresh_cli(server.port, "--solver", "greedy")
    with running_daemon(server) as d:
        port = d.http_port
        s, health, _ = req(port, "GET", "/healthz")
        assert s == 200 and health["status"] == "ready"
        s, ready, _ = req(port, "GET", "/readyz")
        assert s == 200 and ready["ready"]
        s, body, _ = req(port, "POST", "/plan", {})
        assert s == 200 and body["status"] == "ok"
        assert body["result"]["stdout"] == base
        # The envelope IS a schema-v1 run report (plus the result section).
        assert validate_report(body) == []
        assert any(
            sp["name"] == "daemon/request" for sp in body["spans"]
        )
        s, view, _ = req(port, "GET", "/state")
        assert s == 200 and view["lifecycle"] == "ready"
        assert view["topics"] == 2 and view["brokers"] == 4
        s, nf, _ = req(port, "GET", "/nope")
        assert s == 404


def test_plan_params_mirror_cli_flags(server):
    base = fresh_cli(
        server.port, "--solver", "greedy",
        "--broker_hosts_to_remove", "h4", "--topics", "events",
    )
    with running_daemon(server) as d:
        s, body, _ = req(d.http_port, "POST", "/plan", {
            "solver": "greedy",
            "broker_hosts_to_remove": "h4",
            "topics": ["events"],
        })
        assert s == 200
        assert body["result"]["stdout"] == base


def test_plan_tpu_solver_uses_cached_preencode(server):
    base = fresh_cli(server.port, "--solver", "tpu")
    with running_daemon(server, solver="tpu") as d:
        # The post-resync warm hook made the solve programs resident in
        # the background (or failed loudly into its counter).
        assert _await(
            lambda: d.counters().get("daemon.warmups", 0) >= 1
            or d.counters().get("daemon.warmup_failures", 0) >= 1,
            timeout=60,
        )
        assert not d.counters().get("daemon.warmup_failures")
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200 and body["status"] == "ok"
        assert body["result"]["stdout"] == base
        # Narrowing the broker set must ALSO match (preencode bypassed,
        # in-solver encode): identical bytes either way.
        base2 = fresh_cli(
            server.port, "--solver", "tpu",
            "--broker_hosts_to_remove", "h4",
        )
        s, body2, _ = req(d.http_port, "POST", "/plan",
                          {"broker_hosts_to_remove": "h4"})
        assert s == 200 and body2["result"]["stdout"] == base2


def test_bad_requests_are_400_never_500(server):
    with running_daemon(server) as d:
        port = d.http_port
        s, body, _ = req(port, "POST", "/plan", {"topics": ["ghost"]})
        assert s == 400 and body["status"] == "error"
        assert "ghost" in body["error"]["message"]
        s, body, _ = req(port, "POST", "/plan", {"topics": "not-a-list"})
        assert s == 400
        s, body, _ = req(port, "POST", "/plan",
                         {"desired_replication_factor": "three"})
        assert s == 400
        # An explicit JSON null means "infer", exactly like the CLI default.
        s, body, _ = req(port, "POST", "/plan",
                         {"desired_replication_factor": None})
        assert s == 200
        s, body, _ = req(port, "POST", "/plan",
                         {"broker_hosts": "unknown-host"})
        assert s == 400
        # Malformed JSON body.
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/plan", body="{nope")
        assert conn.getresponse().status == 400
        conn.close()
        # The daemon survived all of it.
        s, body, _ = req(port, "GET", "/readyz")
        assert s == 200


def test_whatif_matches_cli_ranking(server):
    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = run(["--zk_string", f"127.0.0.1:{server.port}",
                  "--mode", "RANK_DECOMMISSION"])
    assert rc == 0
    with running_daemon(server) as d:
        s, body, _ = req(d.http_port, "POST", "/whatif", {})
        assert s == 200
        assert body["result"]["stdout"] == out.getvalue()
        s, body, _ = req(d.http_port, "POST", "/whatif",
                         {"scenarios": [[1], ["h3", "h4"]]})
        assert s == 200
        assert "DECOMMISSION RANKING:" in body["result"]["stdout"]


def test_backpressure_sheds_with_retry_after(server):
    with running_daemon(server) as d:
        # Exhaust the inflight gate from outside: every admission slot
        # taken, the next request must shed, not queue.
        for _ in range(d.max_inflight):
            assert d._inflight.acquire(blocking=False)
        try:
            s, body, headers = req(d.http_port, "POST", "/plan", {})
            assert s == 503
            assert headers.get("Retry-After") == "1"
            assert d.counters().get("daemon.requests_shed") == 1
        finally:
            for _ in range(d.max_inflight):
                d._inflight.release()
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200


def test_watchdog_flags_slow_requests(server):
    with running_daemon(server) as d:
        d.request_timeout = 0.0  # every request overruns a zero budget
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200  # flagged, not failed
        assert body["result"]["watchdog_exceeded"] is True
        assert d.counters().get("daemon.watchdog_exceeded") == 1


def test_drain_refuses_and_exits_clean(server):
    d = AssignerDaemon(f"127.0.0.1:{server.port}", solver="greedy")
    d.start()
    port = d.http_port
    d.request_stop()
    s, body, _ = req(port, "GET", "/readyz")
    assert s == 503 and not body["ready"]
    s, body, headers = req(port, "POST", "/plan", {})
    assert s == 503 and body["error"] == "draining"
    d.shutdown()
    assert d.lifecycle() == "stopped"
    # No stranded sockets: the ZK session and the HTTP listener are gone.
    assert getattr(d.backend._zk, "_sock", None) is None
    assert d.httpd.socket.fileno() == -1


# --- watch-driven churn ------------------------------------------------------

def _await(predicate, timeout=10.0, every=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(every)
    return False


def test_churn_updates_cache_and_stays_cli_identical(server):
    with running_daemon(server) as d:
        w = MiniZkClient(f"127.0.0.1:{server.port}")
        w.start()
        try:
            # create (same shape class as the fixture's own topics — the
            # reference-faithful greedy can dead-end on slack-0 topics,
            # which is not what this test is about)
            w.create("/brokers/topics/fresh",
                     b'{"partitions": {"0": [1, 2, 3], "1": [2, 3, 4]}}')
            assert _await(lambda: "fresh" in d.state.topic_names())
            # reassign (data change)
            w.set_data("/brokers/topics/logs",
                       b'{"partitions": {"0": [1, 2]}}')
            assert _await(
                lambda: d.state.assignments(["logs"])["logs"] == {0: [1, 2]}
            )
            # delete
            w.delete("/brokers/topics/events")
            assert _await(lambda: "events" not in d.state.topic_names())
            assert d.counters().get("daemon.reencode.topics", 0) >= 2
            # and the served plan equals a fresh CLI run on the NEW truth
            assert _await(lambda: not d.state.stale)
            base = fresh_cli(server.port, "--solver", "greedy")
            s, body, _ = req(d.http_port, "POST", "/plan", {})
            assert s == 200 and body["result"]["stdout"] == base
        finally:
            w.close()


def test_churn_race_mid_request_retries_to_fresh_truth(server):
    """A topic deleted by the watch thread BETWEEN the request's topic-list
    snapshot and its cache read must not surface as an error: the implicit
    whole-cluster request retries once against the new truth."""
    with running_daemon(server) as d:
        orig = d.state.plan_inputs
        fired = {"n": 0}

        def racy(topic_list, want_encode):
            if fired["n"] == 0:
                fired["n"] += 1
                d.state.apply_topic("logs", None)  # churn wins the race
            return orig(topic_list, want_encode)

        d.state.plan_inputs = racy
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200
        assert '"topic":"logs"' not in body["result"]["stdout"]
        assert '"topic":"events"' in body["result"]["stdout"]
        assert d.counters().get("daemon.churn_retries") == 1


def test_session_loss_recovers_via_resync(server):
    with running_daemon(server) as d:
        assert _await(lambda: not d.state.stale)
        d._expire_session()  # the session:expire seam's mechanics
        assert d.state.stale  # stale-marked immediately
        assert _await(lambda: not d.state.stale)  # re-established + resynced
        assert d.counters().get("daemon.resyncs", 0) >= 2
        base = fresh_cli(server.port, "--solver", "greedy")
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200 and body["status"] == "ok"
        assert body["result"]["stdout"] == base


def test_watchless_interval_resync(server, monkeypatch):
    monkeypatch.setenv("KA_DAEMON_WATCH", "0")
    monkeypatch.setenv("KA_DAEMON_RESYNC_INTERVAL", "0.2")
    with running_daemon(server) as d:
        assert not d._use_watches
        w = MiniZkClient(f"127.0.0.1:{server.port}")
        w.start()
        try:
            w.create("/brokers/topics/later",
                     b'{"partitions": {"0": [1, 2]}}')
            assert _await(lambda: "later" in d.state.topic_names())
        finally:
            w.close()


def test_snapshot_backend_daemon(tmp_path):
    """The daemon serves a snapshot cluster too (watchless): hermetic
    deployments and tests get the same surface."""
    from .jute_server import exec_snapshot_cluster

    snap = tmp_path / "cluster.json"
    snap.write_text(json.dumps(exec_snapshot_cluster()))
    base = fresh_cli(str(snap), "--solver", "greedy")
    d = AssignerDaemon(str(snap), solver="greedy")
    d.start()
    try:
        assert not d._use_watches
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200 and body["status"] == "ok"
        assert body["result"]["stdout"] == base
    finally:
        d.shutdown()
