"""The resident assigner daemon (ISSUE 8): watch-fed cache, incremental
re-encode, the HTTP surface, the supervised lifecycle, and the zkwire watch
protocol underneath it — all against the in-repo jute server (real TCP) or
the hermetic snapshot backend."""
from __future__ import annotations

import contextlib
import http.client
import io
import json
import time

import pytest

from kafka_assigner_tpu import faults
from kafka_assigner_tpu.cli import run
from kafka_assigner_tpu.daemon import AssignerDaemon, CacheBackend, DaemonState
from kafka_assigner_tpu.io.base import BrokerInfo
from kafka_assigner_tpu.io.zkwire import (
    EVENT_CHILDREN_CHANGED,
    EVENT_DATA_CHANGED,
    EVENT_DELETED,
    MiniZkClient,
)
from kafka_assigner_tpu.obs.report import validate_report

from .jute_server import JuteZkServer, cluster_tree


@pytest.fixture(autouse=True)
def _fresh_injector():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(autouse=True)
def _daemon_env(monkeypatch):
    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    monkeypatch.setenv("KA_DAEMON_RESYNC_INTERVAL", "0.5")


@pytest.fixture()
def server():
    s = JuteZkServer(cluster_tree())
    s.start()
    yield s
    s.shutdown()


@contextlib.contextmanager
def running_daemon(server, **kwargs):
    kwargs.setdefault("solver", "greedy")
    d = AssignerDaemon(f"127.0.0.1:{server.port}", **kwargs)
    d.start()
    try:
        yield d
    finally:
        d.shutdown()


def fresh_cli(port_or_path, *extra):
    """A fresh in-process CLI mode-3 run — the byte-identity oracle."""
    zk = (
        port_or_path if isinstance(port_or_path, str)
        else f"127.0.0.1:{port_or_path}"
    )
    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = run(["--zk_string", zk, "--mode", "PRINT_REASSIGNMENT",
                  *extra])
    assert rc == 0, err.getvalue()
    return out.getvalue()


def req(port, method, path, payload=None, timeout=60.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        body = None if payload is None else json.dumps(payload)
        conn.request(method, path, body=body)
        resp = conn.getresponse()
        data = json.loads(resp.read())
        return resp.status, data, dict(resp.getheaders())
    finally:
        conn.close()


# --- zkwire watch protocol ---------------------------------------------------

def test_wire_data_watch_fires_on_set_and_delete(server):
    c = MiniZkClient(f"127.0.0.1:{server.port}")
    c.start()
    w = MiniZkClient(f"127.0.0.1:{server.port}")
    w.start()
    try:
        c.get("/brokers/topics/events", watch=True)
        w.set_data("/brokers/topics/events", b'{"partitions": {"0": [1]}}')
        events = c.poll_watches(timeout=5.0)
        assert [(e.type, e.path) for e in events] == [
            (EVENT_DATA_CHANGED, "/brokers/topics/events")
        ]
        # One-shot: a second mutation without re-arming fires nothing.
        w.set_data("/brokers/topics/events", b'{"partitions": {"0": [2]}}')
        assert c.poll_watches(timeout=0.3) == []
        # Re-arm, then delete → NodeDeleted.
        c.get("/brokers/topics/events", watch=True)
        w.delete("/brokers/topics/events")
        events = c.poll_watches(timeout=5.0)
        assert [(e.type, e.path) for e in events] == [
            (EVENT_DELETED, "/brokers/topics/events")
        ]
    finally:
        c.close()
        w.close()


def test_wire_child_watch_fires_on_create(server):
    c = MiniZkClient(f"127.0.0.1:{server.port}")
    c.start()
    w = MiniZkClient(f"127.0.0.1:{server.port}")
    w.start()
    try:
        kids = c.get_children("/brokers/topics", watch=True)
        assert kids == ["events", "logs"]
        w.create("/brokers/topics/zzz", b'{"partitions": {"0": [1, 2]}}')
        events = c.poll_watches(timeout=5.0)
        assert (EVENT_CHILDREN_CHANGED, "/brokers/topics") in [
            (e.type, e.path) for e in events
        ]
    finally:
        c.close()
        w.close()


def test_wire_watch_notification_between_replies_is_queued(server):
    """A notification landing while a normal read is in flight must not
    desync the xid matching: it queues for poll_watches."""
    c = MiniZkClient(f"127.0.0.1:{server.port}")
    c.start()
    w = MiniZkClient(f"127.0.0.1:{server.port}")
    w.start()
    try:
        c.get("/brokers/topics/logs", watch=True)
        w.set_data("/brokers/topics/logs", b'{"partitions": {"0": [3]}}')
        time.sleep(0.2)  # let the notification hit c's socket buffer
        data, _ = c.get("/brokers/topics/events")  # normal read still works
        assert b"partitions" in data
        events = c.poll_watches(timeout=1.0)
        assert [e.path for e in events] == ["/brokers/topics/logs"]
    finally:
        c.close()
        w.close()


def test_session_generation_bumps_on_restart(server):
    c = MiniZkClient(f"127.0.0.1:{server.port}")
    c.start()
    try:
        g0 = c.session_generation
        assert g0 >= 1
        c.stop()
        c.close()
        c.start()
        assert c.session_generation == g0 + 1
    finally:
        c.close()


# --- DaemonState / CacheBackend ---------------------------------------------

def _state_fixture():
    st = DaemonState()
    brokers = [
        BrokerInfo(id=i, host=f"h{i}", port=9092, rack=f"r{i % 2}")
        for i in range(1, 5)
    ]
    st.reset(brokers, {
        "events": {0: [1, 2], 1: [2, 3]},
        "logs": {0: [3, 4]},
    })
    return st


def test_cache_backend_serves_metadata():
    st = _state_fixture()
    be = CacheBackend(st)
    assert [b.id for b in be.brokers()] == [1, 2, 3, 4]
    assert be.all_topics() == ["events", "logs"]
    assert be.partition_assignment(["logs"]) == {"logs": {0: [3, 4]}}
    assert list(be.fetch_topics(["logs", "ghost"], missing="skip")) == [
        ("logs", {0: [3, 4]}), ("ghost", None),
    ]
    with pytest.raises(KeyError):
        be.partition_assignment(["ghost"])


def test_state_delta_and_plan_inputs():
    st = _state_fixture()
    v0 = st.version
    assert st.apply_topic("fresh", {0: [1, 2, 3]})
    assert st.version == v0 + 1
    initial, pre = st.plan_inputs(["events", "fresh"], want_encode=True)
    assert initial["fresh"] == {0: [1, 2, 3]}
    encs, currents, jh, pr = pre
    assert [e.topic for e in encs] == ["events", "fresh"]
    # delete
    assert not st.apply_topic("fresh", None)
    with pytest.raises(KeyError):
        st.plan_inputs(["fresh"], want_encode=False)


# --- the HTTP surface --------------------------------------------------------

def test_endpoints_and_plan_byte_identity(server):
    base = fresh_cli(server.port, "--solver", "greedy")
    with running_daemon(server) as d:
        port = d.http_port
        s, health, _ = req(port, "GET", "/healthz")
        assert s == 200 and health["status"] == "ready"
        s, ready, _ = req(port, "GET", "/readyz")
        assert s == 200 and ready["ready"]
        s, body, _ = req(port, "POST", "/plan", {})
        assert s == 200 and body["status"] == "ok"
        assert body["result"]["stdout"] == base
        # The envelope IS a schema-v1 run report (plus the result section).
        assert validate_report(body) == []
        assert any(
            sp["name"] == "daemon/request" for sp in body["spans"]
        )
        s, view, _ = req(port, "GET", "/state")
        assert s == 200 and view["lifecycle"] == "ready"
        assert view["topics"] == 2 and view["brokers"] == 4
        s, nf, _ = req(port, "GET", "/nope")
        assert s == 404


def test_plan_params_mirror_cli_flags(server):
    base = fresh_cli(
        server.port, "--solver", "greedy",
        "--broker_hosts_to_remove", "h4", "--topics", "events",
    )
    with running_daemon(server) as d:
        s, body, _ = req(d.http_port, "POST", "/plan", {
            "solver": "greedy",
            "broker_hosts_to_remove": "h4",
            "topics": ["events"],
        })
        assert s == 200
        assert body["result"]["stdout"] == base


def test_plan_tpu_solver_uses_cached_preencode(server):
    base = fresh_cli(server.port, "--solver", "tpu")
    with running_daemon(server, solver="tpu") as d:
        # The post-resync warm hook made the solve programs resident in
        # the background (or failed loudly into its counter).
        assert _await(
            lambda: d.counters().get("daemon.warmups", 0) >= 1
            or d.counters().get("daemon.warmup_failures", 0) >= 1,
            timeout=60,
        )
        assert not d.counters().get("daemon.warmup_failures")
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200 and body["status"] == "ok"
        assert body["result"]["stdout"] == base
        # Narrowing the broker set must ALSO match (preencode bypassed,
        # in-solver encode): identical bytes either way.
        base2 = fresh_cli(
            server.port, "--solver", "tpu",
            "--broker_hosts_to_remove", "h4",
        )
        s, body2, _ = req(d.http_port, "POST", "/plan",
                          {"broker_hosts_to_remove": "h4"})
        assert s == 200 and body2["result"]["stdout"] == base2


def test_bad_requests_are_400_never_500(server):
    with running_daemon(server) as d:
        port = d.http_port
        s, body, _ = req(port, "POST", "/plan", {"topics": ["ghost"]})
        assert s == 400 and body["status"] == "error"
        assert "ghost" in body["error"]["message"]
        s, body, _ = req(port, "POST", "/plan", {"topics": "not-a-list"})
        assert s == 400
        s, body, _ = req(port, "POST", "/plan",
                         {"desired_replication_factor": "three"})
        assert s == 400
        # An explicit JSON null means "infer", exactly like the CLI default.
        s, body, _ = req(port, "POST", "/plan",
                         {"desired_replication_factor": None})
        assert s == 200
        s, body, _ = req(port, "POST", "/plan",
                         {"broker_hosts": "unknown-host"})
        assert s == 400
        # Malformed JSON body.
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/plan", body="{nope")
        assert conn.getresponse().status == 400
        conn.close()
        # The daemon survived all of it.
        s, body, _ = req(port, "GET", "/readyz")
        assert s == 200


def test_whatif_matches_cli_ranking(server):
    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = run(["--zk_string", f"127.0.0.1:{server.port}",
                  "--mode", "RANK_DECOMMISSION"])
    assert rc == 0
    with running_daemon(server) as d:
        s, body, _ = req(d.http_port, "POST", "/whatif", {})
        assert s == 200
        assert body["result"]["stdout"] == out.getvalue()
        s, body, _ = req(d.http_port, "POST", "/whatif",
                         {"scenarios": [[1], ["h3", "h4"]]})
        assert s == 200
        assert "DECOMMISSION RANKING:" in body["result"]["stdout"]


def test_backpressure_sheds_with_retry_after(server, monkeypatch):
    """The inflight gate is per-cluster AND live: KA_DAEMON_MAX_INFLIGHT is
    re-read per request, so an operator can loosen it on a running fleet
    (ISSUE 9 satellite)."""
    with running_daemon(server) as d:
        sup = d.supervisor()
        monkeypatch.setenv("KA_DAEMON_MAX_INFLIGHT", "1")
        # Occupy the single admission slot from outside: the next request
        # must shed, not queue.
        with sup._active_lock:
            sup._active += 1
        try:
            s, body, headers = req(d.http_port, "POST", "/plan", {})
            assert s == 503
            assert body["max_inflight"] == 1
            assert headers.get("Retry-After") == "1"
            assert d.counters().get("daemon.requests_shed") == 1
            # Loosen the gate LIVE — no restart, the same daemon admits.
            monkeypatch.setenv("KA_DAEMON_MAX_INFLIGHT", "2")
            s, body, _ = req(d.http_port, "POST", "/plan", {})
            assert s == 200
        finally:
            with sup._active_lock:
                sup._active -= 1
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200


def test_watchdog_flags_slow_requests(server):
    with running_daemon(server) as d:
        # every request overruns a zero budget (per-cluster override)
        d.supervisor().request_timeout = 0.0
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200  # flagged, not failed
        assert body["result"]["watchdog_exceeded"] is True
        assert d.counters().get("daemon.watchdog_exceeded") == 1


def test_drain_refuses_and_exits_clean(server):
    d = AssignerDaemon(f"127.0.0.1:{server.port}", solver="greedy")
    d.start()
    port = d.http_port
    d.request_stop()
    s, body, _ = req(port, "GET", "/readyz")
    assert s == 503 and not body["ready"]
    s, body, headers = req(port, "POST", "/plan", {})
    assert s == 503 and body["error"] == "draining"
    d.shutdown()
    assert d.lifecycle() == "stopped"
    # No stranded sockets: the ZK session and the HTTP listener are gone.
    assert getattr(d.supervisor().backend._zk, "_sock", None) is None
    assert d.httpd.socket.fileno() == -1


# --- watch-driven churn ------------------------------------------------------

def _await(predicate, timeout=10.0, every=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(every)
    return False


def test_churn_updates_cache_and_stays_cli_identical(server):
    with running_daemon(server) as d:
        sup = d.supervisor()
        w = MiniZkClient(f"127.0.0.1:{server.port}")
        w.start()
        try:
            # create (same shape class as the fixture's own topics — the
            # reference-faithful greedy can dead-end on slack-0 topics,
            # which is not what this test is about)
            w.create("/brokers/topics/fresh",
                     b'{"partitions": {"0": [1, 2, 3], "1": [2, 3, 4]}}')
            assert _await(lambda: "fresh" in sup.state.topic_names())
            # reassign (data change)
            w.set_data("/brokers/topics/logs",
                       b'{"partitions": {"0": [1, 2]}}')
            assert _await(
                lambda: sup.state.assignments(["logs"])["logs"]
                == {0: [1, 2]}
            )
            # delete
            w.delete("/brokers/topics/events")
            assert _await(
                lambda: "events" not in sup.state.topic_names()
            )
            assert d.counters().get("daemon.reencode.topics", 0) >= 2
            # and the served plan equals a fresh CLI run on the NEW truth
            assert _await(lambda: not sup.state.stale)
            base = fresh_cli(server.port, "--solver", "greedy")
            s, body, _ = req(d.http_port, "POST", "/plan", {})
            assert s == 200 and body["result"]["stdout"] == base
        finally:
            w.close()


def test_churn_race_mid_request_retries_to_fresh_truth(server):
    """A topic deleted by the watch thread BETWEEN the request's topic-list
    snapshot and its cache read must not surface as an error: the implicit
    whole-cluster request retries once against the new truth."""
    with running_daemon(server) as d:
        sup = d.supervisor()
        orig = sup.state.plan_inputs
        fired = {"n": 0}

        def racy(topic_list, want_encode):
            if fired["n"] == 0:
                fired["n"] += 1
                sup.state.apply_topic("logs", None)  # churn wins the race
            return orig(topic_list, want_encode)

        sup.state.plan_inputs = racy
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200
        assert '"topic":"logs"' not in body["result"]["stdout"]
        assert '"topic":"events"' in body["result"]["stdout"]
        assert d.counters().get("daemon.churn_retries") == 1


def test_session_loss_recovers_via_resync(server):
    with running_daemon(server) as d:
        sup = d.supervisor()
        assert _await(lambda: not sup.state.stale)
        sup._expire_session()  # the session:expire seam's mechanics
        assert sup.state.stale  # stale-marked immediately
        # re-established + resynced
        assert _await(lambda: not sup.state.stale)
        assert d.counters().get("daemon.resyncs", 0) >= 2
        base = fresh_cli(server.port, "--solver", "greedy")
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200 and body["status"] == "ok"
        assert body["result"]["stdout"] == base


def test_watchless_interval_resync(server, monkeypatch):
    monkeypatch.setenv("KA_DAEMON_WATCH", "0")
    monkeypatch.setenv("KA_DAEMON_RESYNC_INTERVAL", "0.2")
    with running_daemon(server) as d:
        sup = d.supervisor()
        assert not sup._use_watches
        w = MiniZkClient(f"127.0.0.1:{server.port}")
        w.start()
        try:
            w.create("/brokers/topics/later",
                     b'{"partitions": {"0": [1, 2]}}')
            assert _await(lambda: "later" in sup.state.topic_names())
        finally:
            w.close()


def test_snapshot_backend_daemon(tmp_path):
    """The daemon serves a snapshot cluster too (watchless): hermetic
    deployments and tests get the same surface."""
    from .jute_server import exec_snapshot_cluster

    snap = tmp_path / "cluster.json"
    snap.write_text(json.dumps(exec_snapshot_cluster()))
    base = fresh_cli(str(snap), "--solver", "greedy")
    d = AssignerDaemon(str(snap), solver="greedy")
    d.start()
    try:
        assert not d.supervisor()._use_watches
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200 and body["status"] == "ok"
        assert body["result"]["stdout"] == base
    finally:
        d.shutdown()


def test_snapshot_topic_order_canonical(tmp_path):
    """Topic ORDER is canonicalized at the backend boundary (ISSUE 15
    satellite): a snapshot whose file lists >10 numerically-named topics
    WITHOUT zero-padding (so lexicographic != insertion order) must serve
    the same stdout bytes from the daemon cache (sorted by construction)
    and from a fresh CLI run over the file — the pre-existing ordering
    dependence ISSUE 14's bench had to zero-pad around."""
    from kafka_assigner_tpu.io.snapshot import SnapshotBackend

    snap = tmp_path / "many.json"
    snap.write_text(json.dumps({
        "brokers": [
            {"id": i, "host": f"b{i}", "port": 9092, "rack": f"r{i % 2}"}
            for i in range(4)
        ],
        # File order t0, t1, ... t11: lexicographic order interleaves
        # (t0, t1, t10, t11, t2, ...), so an insertion-order listing
        # diverges from the cache's sorted one.
        "topics": {
            f"t{t}": {str(p): [(t + p) % 4, (t + p + 1) % 4]
                      for p in range(2)}
            for t in range(12)
        },
    }))
    assert SnapshotBackend(str(snap)).all_topics() == sorted(
        f"t{t}" for t in range(12)
    )
    base = fresh_cli(str(snap), "--solver", "greedy")
    d = AssignerDaemon(str(snap), solver="greedy")
    d.start()
    try:
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200 and body["status"] == "ok"
        assert body["result"]["stdout"] == base
    finally:
        d.shutdown()


# --- ISSUE 9: multi-cluster supervisors, bulkheads, breakers, /execute ------

import os
import shutil
import threading

from kafka_assigner_tpu.cli import execute as cli_execute
from kafka_assigner_tpu.faults.inject import FaultInjector, parse_spec

from .jute_server import exec_snapshot_cluster


@pytest.fixture()
def server2():
    s = JuteZkServer(cluster_tree())
    s.start()
    yield s
    s.shutdown()


@contextlib.contextmanager
def running_multi(clusters, **kwargs):
    kwargs.setdefault("solver", "greedy")
    d = AssignerDaemon(clusters=clusters, **kwargs)
    d.start()
    try:
        yield d
    finally:
        d.shutdown()


def stream_execute(port, path, payload, timeout=120.0):
    """POST an /execute request; returns (status, events-or-error-body)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload))
        resp = conn.getresponse()
        raw = resp.read().decode("utf-8")
        if resp.status != 200:
            return resp.status, json.loads(raw)
        return resp.status, [json.loads(ln) for ln in raw.splitlines()]
    finally:
        conn.close()


def test_wire_sessions_are_independent(server, server2):
    """N independent sessions, per-session watch queues: a mutation on one
    quorum fires only ITS client's watches, and session generations advance
    independently — the zkwire property the multi-cluster daemon's
    per-supervisor sessions are built on."""
    c1 = MiniZkClient(f"127.0.0.1:{server.port}")
    c2 = MiniZkClient(f"127.0.0.1:{server2.port}")
    w = MiniZkClient(f"127.0.0.1:{server2.port}")
    c1.start(); c2.start(); w.start()
    try:
        c1.get("/brokers/topics/logs", watch=True)
        c2.get("/brokers/topics/logs", watch=True)
        w.set_data("/brokers/topics/logs", b'{"partitions": {"0": [9]}}')
        assert [e.path for e in c2.poll_watches(timeout=5.0)] == [
            "/brokers/topics/logs"
        ]
        assert c1.poll_watches(timeout=0.3) == []  # other quorum: silent
        g1, g2 = c1.session_generation, c2.session_generation
        c1.stop(); c1.close(); c1.start()
        assert c1.session_generation == g1 + 1
        assert c2.session_generation == g2  # untouched
    finally:
        c1.close(); c2.close(); w.close()


def test_multicluster_routing_and_aggregates(server, server2):
    base_a = fresh_cli(server.port, "--solver", "greedy")
    base_b = fresh_cli(server2.port, "--solver", "greedy")
    clusters = {
        "a": f"127.0.0.1:{server.port}",
        "b": f"127.0.0.1:{server2.port}",
    }
    with running_multi(clusters) as d:
        port = d.http_port
        s, body, _ = req(port, "POST", "/clusters/a/plan", {})
        assert s == 200 and body["status"] == "ok"
        assert body["result"]["stdout"] == base_a
        assert body["result"]["cluster"] == "a"
        s, body, _ = req(port, "POST", "/clusters/b/plan", {})
        assert s == 200 and body["result"]["stdout"] == base_b
        # bare data paths refuse with the cluster list
        s, body, _ = req(port, "POST", "/plan", {})
        assert s == 400 and body["clusters"] == ["a", "b"]
        # unknown cluster: 404 naming the known ones
        s, body, _ = req(port, "POST", "/clusters/nope/plan", {})
        assert s == 404 and body["clusters"] == ["a", "b"]
        # aggregates
        s, h, _ = req(port, "GET", "/healthz")
        assert s == 200 and h["status"] == "ready"
        assert set(h["clusters"]) == {"a", "b"}
        assert h["clusters"]["a"]["breaker"]["state"] == "closed"
        s, r, _ = req(port, "GET", "/readyz")
        assert s == 200 and r["ready"]
        s, r, _ = req(port, "GET", "/clusters/b/readyz")
        assert s == 200 and r["ready"]
        s, st, _ = req(port, "GET", "/state")
        assert s == 200 and set(st["clusters"]) == {"a", "b"}
        # per-request obs spans carry the cluster label in multi mode
        s, body, _ = req(port, "POST", "/clusters/a/plan", {})
        assert any(
            sp["name"] == "daemon/request@a" for sp in body["spans"]
        )


def test_bulkhead_isolation_expiry_and_stall_on_a(server, server2):
    """The acceptance bulkhead proof, in-process: session:expire@a +
    resync:stall@a leave cluster B's concurrent /plan responses ok and
    byte-identical THROUGHOUT — A sheds or stale-serves alone."""
    base_a = fresh_cli(server.port, "--solver", "greedy")
    base_b = fresh_cli(server2.port, "--solver", "greedy")
    faults.install(FaultInjector(parse_spec(
        "session@a:1=expire;resync@a:1=stall"
    )))
    clusters = {
        "a": f"127.0.0.1:{server.port}",
        "b": f"127.0.0.1:{server2.port}",
    }
    with running_multi(clusters) as d:
        port = d.http_port
        s, body, _ = req(port, "POST", "/clusters/a/plan", {})
        assert s == 200 and body["status"] == "ok"
        # request #1 on a: the injected expiry lands mid-request —
        # stale-marked, still byte-identical
        s, body, _ = req(port, "POST", "/clusters/a/plan", {})
        assert s == 200 and body["result"]["stdout"] == base_a
        assert body["status"] == "degraded"
        # hammer B from a concurrent thread while A recovers (its first
        # resync attempt stalls by schedule)
        b_failures = []

        def hammer_b():
            for _ in range(8):
                try:
                    s2, b2, _ = req(port, "POST", "/clusters/b/plan", {})
                except OSError as e:
                    b_failures.append(f"transport: {e}")
                    return
                if s2 != 200 or b2["status"] != "ok" \
                        or b2["result"]["stdout"] != base_b:
                    b_failures.append(
                        f"http={s2} status={b2.get('status')!r} "
                        f"identical="
                        f"{b2.get('result', {}).get('stdout') == base_b}"
                    )

        t = threading.Thread(target=hammer_b)
        t.start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            s, body, _ = req(port, "POST", "/clusters/a/plan", {})
            assert s == 200 and body["result"]["stdout"] == base_a
            if body["status"] == "ok":
                break
            time.sleep(0.2)
        t.join(timeout=30)
        assert not t.is_alive()
        assert body["status"] == "ok", "cluster a never recovered"
        assert b_failures == [], b_failures
        assert d.supervisors["a"].counters().get(
            "daemon.resync_failures", 0
        ) >= 1
        assert not d.supervisors["b"].counters().get("daemon.session_lost")


def test_breaker_opens_probes_and_closes(monkeypatch):
    """Quorum blackout: consecutive resync failures open the per-cluster
    breaker (requests stale-serve), the cooldown half-opens it for probes,
    and the quorum's return closes it — /healthz shows every state."""
    monkeypatch.setenv("KA_DAEMON_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("KA_DAEMON_BREAKER_COOLDOWN", "0.2")
    monkeypatch.setenv("KA_DAEMON_RESYNC_INTERVAL", "0.3")
    monkeypatch.setenv("KA_DAEMON_RESYNC_RETRIES", "1")
    monkeypatch.setenv("KA_ZK_CONNECT_RETRIES", "1")
    # 1, not 0: the wire client's transparent re-establishment IS how a
    # breaker probe reaches the returned quorum; 0 would pin the socket
    # dead forever.
    monkeypatch.setenv("KA_ZK_SESSION_RETRIES", "1")
    s1 = JuteZkServer(cluster_tree())
    s1.start()
    zk_port = s1.port
    base = fresh_cli(zk_port, "--solver", "greedy")
    with running_multi({"west": f"127.0.0.1:{zk_port}"}) as d:
        port = d.http_port
        s, body, _ = req(port, "POST", "/clusters/west/plan", {})
        assert s == 200 and body["status"] == "ok"
        s1.shutdown()  # blackout
        assert _await(
            lambda: req(port, "GET", "/clusters/west/healthz")[1]
            ["breaker"]["state"] == "open",
            timeout=20,
        ), "breaker never opened"
        # stale-served, never an error, bytes intact
        s, body, _ = req(port, "POST", "/clusters/west/plan", {})
        assert s == 200 and body["status"] == "degraded"
        assert body["result"]["stdout"] == base
        # quorum returns on the SAME port: a half-open probe closes it
        # (bind may race the old connections' teardown — retry briefly)
        s2 = None
        bind_deadline = time.monotonic() + 10
        while s2 is None:
            try:
                s2 = JuteZkServer(cluster_tree(), port=zk_port)
            except OSError:
                if time.monotonic() > bind_deadline:
                    raise
                time.sleep(0.2)
        s2.start()
        try:
            assert _await(
                lambda: req(port, "GET", "/clusters/west/healthz")[1]
                ["breaker"]["state"] == "closed",
                timeout=20,
            ), "breaker never closed after the quorum returned"
            assert _await(
                lambda: req(port, "POST", "/clusters/west/plan", {})[1]
                ["status"] == "ok",
                timeout=20,
            )
            s, body, _ = req(port, "POST", "/clusters/west/plan", {})
            assert body["result"]["stdout"] == base
            counters = d.supervisors["west"].counters()
            assert counters.get("daemon.breaker_opened", 0) >= 1
            assert counters.get("daemon.breaker_closed", 0) >= 1
        finally:
            s2.shutdown()


def test_double_session_expiry_during_resync(server, monkeypatch):
    """ISSUE 9 satellite: expire -> re-arm -> expire AGAIN before the
    resync completes must land in degraded-not-error, with watches
    re-armed exactly once per session generation (pinned via
    session_generation and the session:expire seam's mechanics)."""
    monkeypatch.setenv("KA_DAEMON_RESYNC_INTERVAL", "30")  # no interval noise
    with running_daemon(server) as d:
        sup = d.supervisor()
        assert _await(lambda: not sup.state.stale)
        be = sup.backend
        gen0 = be.session_generation()
        arm_gens = []
        orig_arm = be.watch_brokers

        def recording_arm():
            out = orig_arm()
            arm_gens.append(be.session_generation())
            return out

        be.watch_brokers = recording_arm
        kills = {"left": 1}
        orig_list = be.watch_topic_list

        def killing_list():
            if kills["left"] > 0:
                kills["left"] -= 1
                sup._expire_session()  # the SECOND expiry, mid-resync
            return orig_list()

        be.watch_topic_list = killing_list
        sup._expire_session()  # the first expiry
        # degraded-not-error while the double-expired resync converges
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200 and body["status"] in ("degraded", "ok")
        assert _await(lambda: not sup.state.stale, timeout=30), \
            "resync never completed after the double expiry"
        # each completed arm belongs to a distinct generation: watches are
        # re-armed exactly once per generation, never twice
        assert len(arm_gens) == len(set(arm_gens)), arm_gens
        assert sup._armed_generation == be.session_generation()
        assert be.session_generation() > gen0 + 1  # both expiries landed
        assert sup.counters().get("daemon.resync_failures", 0) >= 1
        base = fresh_cli(server.port, "--solver", "greedy")
        s, body, _ = req(d.http_port, "POST", "/plan", {})
        assert s == 200 and body["status"] == "ok"
        assert body["result"]["stdout"] == base


def test_execute_endpoint_end_to_end(tmp_path, monkeypatch):
    """Bare /execute on a single-cluster snapshot daemon: streams the
    exec.* event family and converges the cluster byte-identically to an
    offline ka-execute run of the same plan."""
    for k, v in (("KA_EXEC_WAVE_SIZE", "3"),
                 ("KA_EXEC_POLL_INTERVAL", "0.01"),
                 ("KA_EXEC_POLL_TIMEOUT", "10"),
                 ("KA_EXEC_SIM_POLLS", "1"),
                 ("KA_DAEMON_JOURNAL_DIR", str(tmp_path))):
        monkeypatch.setenv(k, v)
    snap = tmp_path / "cluster.json"
    snap.write_text(json.dumps(exec_snapshot_cluster()))
    plan_text = fresh_cli(str(snap), "--solver", "greedy",
                          "--broker_hosts_to_remove", "h9")
    offline = tmp_path / "offline.json"
    shutil.copy(snap, offline)
    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        plan_file = tmp_path / "plan.txt"
        plan_file.write_text(plan_text)
        rc = cli_execute([
            "--zk_string", str(offline), "--plan", str(plan_file),
            "--journal", str(tmp_path / "offline.journal"),
        ])
    assert rc == 0, err.getvalue()
    final_offline = offline.read_text()

    d = AssignerDaemon(str(snap), solver="greedy")
    d.start()
    try:
        s, events = stream_execute(d.http_port, "/execute",
                                   {"plan_text": plan_text})
        assert s == 200
        kinds = [e["event"] for e in events]
        assert kinds[0] == "exec/start"
        assert "exec/wave" in kinds and "exec/wave.committed" in kinds
        assert "exec/verify" in kinds
        done = events[-1]
        assert done["event"] == "exec/done"
        assert done["status"] == "ok" and done["exit_code"] == 0
        assert done["plan"]["skipped_moves"] == []
        assert snap.read_text() == final_offline
        # journal identity: cluster spec stamped, default path per cluster
        journals = [p for p in os.listdir(tmp_path)
                    if p.startswith("ka-execute-default-")]
        assert len(journals) == 1
        j = json.loads((tmp_path / journals[0]).read_text())
        assert j["cluster"] == str(snap) and j["status"] == "complete"
        # single-flight: a held lock means 409 for the next attempt
        sup = d.supervisor()
        assert sup._exec_lock.acquire(blocking=False)
        try:
            s, body = stream_execute(d.http_port, "/execute",
                                     {"plan_text": plan_text})
            assert s == 409 and "single-flight" in body["error"]
        finally:
            sup._exec_lock.release()
        # validation refusals are 400, lock released again afterwards
        s, body = stream_execute(d.http_port, "/execute", {})
        assert s == 400 and "plan" in body["error"]
        assert not sup._exec_lock.locked()
        s, body = stream_execute(
            d.http_port, "/execute",
            {"plan_text": plan_text, "failure_policy": "nope"},
        )
        assert s == 400
    finally:
        d.shutdown()
