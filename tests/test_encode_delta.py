"""Delta-update equivalence of the group encode (ISSUE 8 satellite): after
ANY churn sequence applied through ``GroupEncodeAccumulator``'s delta API
(topic added / deleted / grown / reassigned), ``merge(topic_order)`` must be
byte-identical to a from-scratch ``encode_topic_group`` of the final state —
the daemon's incremental re-encode can never drift from what a fresh process
would compute."""
from __future__ import annotations

import random

import numpy as np
import pytest

from kafka_assigner_tpu.models.problem import (
    GroupEncodeAccumulator,
    encode_topic_group,
)

BROKERS = set(range(1, 10))
RACKS = {b: f"r{(b - 1) % 3}" for b in BROKERS}


def _random_topic(rng, name):
    p = rng.randint(1, 30)
    rf = rng.randint(1, 3)
    return name, {
        pid: rng.sample(sorted(BROKERS), rf) for pid in range(p)
    }


def _assert_merge_equals_scratch(acc, topics):
    order = sorted(topics)
    encs_d, cur_d, jh_d, pr_d = acc.merge(order)
    encs_s, cur_s, jh_s, pr_s = encode_topic_group(
        [(t, topics[t]) for t in order], RACKS, BROKERS,
        [0] * len(order),
    )
    np.testing.assert_array_equal(cur_d, cur_s)
    np.testing.assert_array_equal(jh_d, jh_s)
    np.testing.assert_array_equal(pr_d, pr_s)
    assert cur_d.tobytes() == cur_s.tobytes()  # byte identity, literally
    assert [e.topic for e in encs_d] == [e.topic for e in encs_s]
    for ed, es in zip(encs_d, encs_s):
        assert ed.p == es.p and ed.p_pad == es.p_pad
        assert ed.jhash == es.jhash
        np.testing.assert_array_equal(ed.partition_ids, es.partition_ids)
        np.testing.assert_array_equal(ed.current, es.current)


@pytest.mark.parametrize("seed", range(8))
def test_randomized_churn_matches_from_scratch(seed):
    rng = random.Random(seed)
    acc = GroupEncodeAccumulator(RACKS, BROKERS)
    topics = {}
    next_id = 0
    # seed population
    for _ in range(rng.randint(1, 6)):
        name, cur = _random_topic(rng, f"t{next_id}")
        next_id += 1
        topics[name] = cur
        acc.update_topics([(name, cur)])
    for _step in range(25):
        op = rng.random()
        if op < 0.35 or not topics:  # add
            name, cur = _random_topic(rng, f"t{next_id}")
            next_id += 1
            topics[name] = cur
            acc.update_topics([(name, cur)])
        elif op < 0.55:  # delete
            name = rng.choice(sorted(topics))
            del topics[name]
            assert acc.delete_topic(name)
        else:  # grow / reassign in place
            name = rng.choice(sorted(topics))
            _, cur = _random_topic(rng, name)
            topics[name] = cur
            acc.update_topics([(name, cur)])
    _assert_merge_equals_scratch(acc, topics)


def test_merge_is_non_destructive_and_order_sensitive():
    acc = GroupEncodeAccumulator(RACKS, BROKERS)
    a = {0: [1, 2], 1: [2, 3]}
    b = {0: [4, 5, 6]}
    acc.update_topics([("a", a), ("b", b)])
    first = acc.merge(["a", "b"])
    again = acc.merge(["a", "b"])
    np.testing.assert_array_equal(first[1], again[1])
    # A different order is a different (still exact) encode.
    swapped = acc.merge(["b", "a"])
    _, cur_s, jh_s, _ = encode_topic_group(
        [("b", b), ("a", a)], RACKS, BROKERS, [0, 0]
    )
    np.testing.assert_array_equal(swapped[1], cur_s)
    np.testing.assert_array_equal(swapped[2], jh_s)


def test_shrink_after_giant_topic_shrinks_buckets():
    """A deleted giant topic must not inflate later merges: the delta store
    trims each entry to its OWN buckets, so group buckets come from the
    live topics only — exactly like a from-scratch encode."""
    acc = GroupEncodeAccumulator(RACKS, BROKERS)
    giant = {p: [1, 2, 3] for p in range(200)}
    small = {0: [1, 2]}
    # Encoded TOGETHER in one chunk: the giant's slab must not leak into
    # the small topic's stored entry.
    acc.update_topics([("giant", giant), ("small", small)])
    acc.delete_topic("giant")
    encs, cur, jh, pr = acc.merge(["small"])
    _, cur_s, jh_s, pr_s = encode_topic_group(
        [("small", small)], RACKS, BROKERS, [0]
    )
    assert cur.shape == cur_s.shape  # 8-row bucket, not 200+
    np.testing.assert_array_equal(cur, cur_s)


def test_merge_unknown_topic_raises():
    acc = GroupEncodeAccumulator(RACKS, BROKERS)
    acc.update_topics([("known", {0: [1, 2]})])
    with pytest.raises(KeyError, match="ghost"):
        acc.merge(["known", "ghost"])


def test_duplicate_topic_occurrences_in_order():
    acc = GroupEncodeAccumulator(RACKS, BROKERS)
    cur = {0: [1, 2], 1: [3, 4]}
    acc.update_topics([("dup", cur)])
    encs, cur_d, jh_d, pr_d = acc.merge(["dup", "dup"])
    _, cur_s, jh_s, pr_s = encode_topic_group(
        [("dup", cur), ("dup", cur)], RACKS, BROKERS, [0, 0]
    )
    np.testing.assert_array_equal(cur_d, cur_s)
    np.testing.assert_array_equal(pr_d, pr_s)


def test_empty_merge_matches_empty_finish_shape():
    acc = GroupEncodeAccumulator(RACKS, BROKERS)
    encs, cur, jh, pr = acc.merge([])
    assert encs == [] and cur.shape == (1, 8, 2)


def test_delta_and_streaming_chunks_coexist():
    """The streaming add()/finish() path and the delta store are
    independent: using one never corrupts the other."""
    acc = GroupEncodeAccumulator(RACKS, BROKERS)
    stream = [(f"s{i}", {0: [1, 2], 1: [2, 3]}) for i in range(3)]
    acc.add(stream)
    acc.update_topics([("d0", {0: [4, 5]})])
    encs, cur, jh, pr = acc.finish()
    _, cur_s, _, _ = encode_topic_group(stream, RACKS, BROKERS, [0] * 3)
    np.testing.assert_array_equal(cur, cur_s)
    # The delta store still serves after finish() cleared the chunks.
    d = acc.merge(["d0"])
    _, cur_d, _, _ = encode_topic_group(
        [("d0", {0: [4, 5]})], RACKS, BROKERS, [0]
    )
    np.testing.assert_array_equal(d[1], cur_d)
