"""Saturation-robustness coverage: fresh placement and strand-rescue
decommissions — capabilities the reference provably lacks
(``KafkaAssignmentStrategy.java:29-30`` caveat; its first-fit dead-ends).

These tests previously lived in test_sinkhorn.py; the Sinkhorn estimator was
deleted (measured: no winning regime, see PARITY.md) but the behaviors here
are live, README-advertised paths of the balance-wave chain.
"""
from __future__ import annotations

import pytest

from kafka_assigner_tpu.solvers.tpu import TpuSolver

from .helpers import verify_full_invariants


def test_fresh_assignment_where_greedy_dead_ends():
    # 50 partitions x RF=3 over 10 brokers / 5 racks: the reference's greedy
    # first-fit provably cannot place this from scratch (verified in round-1
    # analysis); the capacity-greedy balance waves must.
    brokers = set(range(100, 110))
    racks = {b: f"rack{b % 5}" for b in brokers}
    solver = TpuSolver()
    out = solver.fresh_assignment("fresh", 50, brokers, racks, 3)
    assert set(out) == set(range(50))
    verify_full_invariants(out, racks, sorted(brokers), 3)


def test_fresh_assignment_balances_load():
    brokers = set(range(20))
    racks = {b: f"r{b % 4}" for b in brokers}
    out = TpuSolver().fresh_assignment("t", 40, brokers, racks, 2)
    loads = {}
    for r in out.values():
        for b in r:
            loads[b] = loads.get(b, 0) + 1
    # cap = ceil(80/20) = 4; perfect balance respects the cap everywhere
    assert max(loads.values()) <= 4
    assert min(loads.values()) >= 2


def test_reassignment_succeeds_where_reference_strands():
    # Rack-unaware 10 -> 8 broker decommission of a striped cluster: the
    # reference's first-fit strands ("Partition 49 could not be fully
    # assigned!"); the tpu solver's balance fallback completes it with
    # exactly minimal movement (only the dead brokers' replicas).
    from kafka_assigner_tpu.assigner import TopicAssigner

    from .helpers import moved_replicas

    n, p, rf = 10, 50, 3
    base = list(range(n))
    cur = {q: [base[(q + i) % n] for i in range(rf)] for q in range(p)}
    live = set(base[2:])
    with pytest.raises(ValueError, match="could not be fully assigned"):
        TopicAssigner("greedy").generate_assignment("t", cur, live, {}, -1)
    new = TopicAssigner("tpu").generate_assignment("t", cur, live, {}, -1)
    verify_full_invariants(new, {}, sorted(live), rf)
    lost = sum(1 for r in cur.values() for b in r if b not in live)
    assert moved_replicas(cur, new) == lost  # minimal movement
