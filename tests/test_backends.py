"""Backend dispatch + znode endpoint parsing + snapshot round-trip."""
from __future__ import annotations

import json

import pytest

from kafka_assigner_tpu.io.base import BrokerInfo, open_backend
from kafka_assigner_tpu.io.snapshot import SnapshotBackend, write_snapshot
from kafka_assigner_tpu.io.zk import _resolve_endpoint


def test_open_backend_dispatch(tmp_path):
    path = tmp_path / "c.json"
    path.write_text(json.dumps({"brokers": [], "topics": {}}))
    assert isinstance(open_backend(f"file://{path}"), SnapshotBackend)
    assert isinstance(open_backend(str(path)), SnapshotBackend)
    # Without kazoo, the zk path falls back to the in-tree wire client
    # (io/zkwire.py), which fails with a clear session error on an
    # unreachable quorum instead of a missing-dependency error.
    with pytest.raises(RuntimeError, match="ZooKeeper session|kazoo"):
        open_backend("zkhost-does-not-resolve:2181")
    # The AdminClient bridge stays gated on its client libraries.
    with pytest.raises(RuntimeError, match="confluent-kafka|kafka-python"):
        open_backend("kafka://broker:9092")
    # Forcing kazoo when it is not installed is a loud error, not a silent
    # fallback.
    import os

    if "kazoo" not in __import__("sys").modules:
        os.environ["KA_ZK_CLIENT"] = "kazoo"
        try:
            with pytest.raises(RuntimeError, match="kazoo"):
                open_backend("zkhost:2181")
        finally:
            del os.environ["KA_ZK_CLIENT"]


def test_snapshot_round_trip(tmp_path):
    path = str(tmp_path / "c.json")
    brokers = [BrokerInfo(1, "h1", 9092, "a"), BrokerInfo(2, "h2", 9093, None)]
    topics = {"t": {0: [1, 2], 1: [2, 1]}}
    write_snapshot(path, brokers, topics)
    backend = SnapshotBackend(path)
    assert backend.brokers() == brokers
    assert backend.all_topics() == ["t"]
    assert backend.partition_assignment(["t"]) == topics
    with pytest.raises(KeyError, match="not in snapshot"):
        backend.partition_assignment(["missing"])


def test_zk_endpoint_resolution():
    # Plain pre-0.9 znode: top-level host/port.
    assert _resolve_endpoint({"host": "h", "port": 9092}, "1") == ("h", 9092)
    # Multi-listener znode: host null, endpoints list (Kafka >= 0.9).
    meta = {"host": None, "endpoints": ["SSL://secure-host:9093"]}
    assert _resolve_endpoint(meta, "1") == ("secure-host", 9093)
    # IPv6-ish / multiple endpoints: first parseable wins.
    meta = {"host": None, "endpoints": ["PLAINTEXT://h1:9092", "SSL://h1:9093"]}
    assert _resolve_endpoint(meta, "1") == ("h1", 9092)
    # Nothing resolvable: loud failure, never an empty hostname.
    with pytest.raises(ValueError, match="no resolvable host"):
        _resolve_endpoint({"host": None, "endpoints": []}, "7")


def test_cli_validates_solver_before_output(tmp_path, capsys, monkeypatch):
    """--solver must be validated before any metadata read or stdout output."""
    from kafka_assigner_tpu.cli import run_tool
    from kafka_assigner_tpu.solvers import base as solver_base

    path = tmp_path / "c.json"
    path.write_text(json.dumps(
        {"brokers": [{"id": 1, "host": "h", "port": 1}], "topics": {"t": {"0": [1]}}}
    ))

    def broken_get_solver(name):
        raise NotImplementedError("backend unavailable")

    monkeypatch.setattr("kafka_assigner_tpu.cli.get_solver", broken_get_solver)
    with pytest.raises(NotImplementedError):
        run_tool(["--zk_string", str(path), "--mode", "PRINT_REASSIGNMENT"])
    # No partial rollback snapshot was emitted before the failure.
    assert capsys.readouterr().out == ""


# ---------------------------------------------------------------------------
# Fake-client happy-path coverage for the live backends (VERDICT round 1 #9):
# an in-memory kazoo stub (znode dict) and stub admin modules drive the full
# parsing logic hermetically — the layer the reference leaves untested.
# ---------------------------------------------------------------------------

def _install_fake_kazoo(monkeypatch, znodes):
    """Install a minimal in-memory kazoo: znodes maps dir path -> {name: data}."""
    import sys
    import types

    class FakeKazooClient:
        instances = []

        def __init__(self, hosts, timeout):
            self.hosts, self.timeout = hosts, timeout
            self.started = self.stopped = self.closed = False
            FakeKazooClient.instances.append(self)

        def start(self, timeout=None):
            self.started = True

        def get_children(self, path):
            return list(znodes[path])

        def get(self, path):
            parent, _, name = path.rpartition("/")
            return znodes[parent][name].encode(), object()

        def stop(self):
            self.stopped = True

        def close(self):
            self.closed = True

    pkg = types.ModuleType("kazoo")
    client_mod = types.ModuleType("kazoo.client")
    client_mod.KazooClient = FakeKazooClient
    pkg.client = client_mod
    monkeypatch.setitem(sys.modules, "kazoo", pkg)
    monkeypatch.setitem(sys.modules, "kazoo.client", client_mod)
    return FakeKazooClient


def test_zk_backend_happy_path_with_fake_kazoo(monkeypatch):
    from kafka_assigner_tpu.io.zk import ZkBackend

    znodes = {
        "/brokers/ids": {
            "2": json.dumps(
                {"host": None, "endpoints": ["PLAINTEXT://h2:9093"], "rack": None}
            ),
            "10": json.dumps({"host": "h10", "port": 9092, "rack": "rb"}),
            "1": json.dumps({"host": "h1", "port": 9092, "rack": "ra"}),
        },
        "/brokers/topics": {
            "events": json.dumps({"partitions": {"1": [2, 1], "0": [1, 2]}}),
            "logs": json.dumps({"partitions": {"0": [10, 2]}}),
        },
    }
    fake = _install_fake_kazoo(monkeypatch, znodes)
    backend = ZkBackend("zkhost:2181")
    client = fake.instances[-1]
    assert client.started and client.timeout == 10.0  # reference's 10s timeout

    # Numeric id order (int sort, not lexicographic: 1, 2, 10).
    assert backend.brokers() == [
        BrokerInfo(1, "h1", 9092, "ra"),
        BrokerInfo(2, "h2", 9093, None),  # endpoint-resolved, rack null
        BrokerInfo(10, "h10", 9092, "rb"),
    ]
    assert backend.all_topics() == ["events", "logs"]
    assert backend.partition_assignment(["events"]) == {
        "events": {0: [1, 2], 1: [2, 1]}
    }
    backend.close()
    assert client.stopped and client.closed


def _install_fake_confluent(monkeypatch):
    import sys
    import types

    class _Obj:
        def __init__(self, **kw):
            self.__dict__.update(kw)

    md = _Obj(
        brokers={
            2: _Obj(id=2, host="h2", port=9093),
            1: _Obj(id=1, host="h1", port=9092),
        },
        topics={
            "events": _Obj(
                partitions={
                    1: _Obj(replicas=[2, 1]),
                    0: _Obj(replicas=[1, 2]),
                }
            ),
            "logs": _Obj(partitions={0: _Obj(replicas=[2])}),
        },
    )

    class AdminClient:
        def __init__(self, conf):
            self.conf = conf

        def list_topics(self, timeout=None):
            return md

    pkg = types.ModuleType("confluent_kafka")
    admin_mod = types.ModuleType("confluent_kafka.admin")
    admin_mod.AdminClient = AdminClient
    pkg.admin = admin_mod
    monkeypatch.setitem(sys.modules, "confluent_kafka", pkg)
    monkeypatch.setitem(sys.modules, "confluent_kafka.admin", admin_mod)


def test_kafka_admin_confluent_branch(monkeypatch, capsys):
    from kafka_assigner_tpu.io.kafka_admin import KafkaAdminBackend

    _install_fake_confluent(monkeypatch)
    backend = KafkaAdminBackend("b1:9092")
    assert backend._impl == "confluent"
    assert backend.brokers() == [
        BrokerInfo(1, "h1", 9092, None),
        BrokerInfo(2, "h2", 9093, None),
    ]
    # ADVICE round 1 (medium): the confluent path is rack-blind and must say
    # so loudly on stderr — exactly once.
    backend.brokers()
    err = capsys.readouterr().err
    assert err.count("rack") >= 1 and err.count("WARNING") == 1
    assert backend.all_topics() == ["events", "logs"]
    assert backend.partition_assignment(["events", "logs"]) == {
        "events": {0: [1, 2], 1: [2, 1]},
        "logs": {0: [2]},
    }
    backend.close()  # no-op for confluent


def test_kafka_admin_kafka_python_branch(monkeypatch):
    import sys
    import types

    from kafka_assigner_tpu.io.kafka_admin import KafkaAdminBackend

    closed = []

    class KafkaAdminClient:
        def __init__(self, bootstrap_servers):
            self.bootstrap_servers = bootstrap_servers

        def describe_cluster(self):
            return {
                "brokers": [
                    {"node_id": 2, "host": "h2", "port": 9093, "rack": "rb"},
                    {"node_id": 1, "host": "h1", "port": 9092},
                ]
            }

        def list_topics(self):
            return ["logs", "events"]

        def describe_topics(self, topics):
            data = {
                "events": [
                    {"partition": 1, "replicas": [2, 1]},
                    {"partition": 0, "replicas": [1, 2]},
                ],
                "logs": [{"partition": 0, "replicas": [2]}],
            }
            return [{"topic": t, "partitions": data[t]} for t in topics]

        def close(self):
            closed.append(True)

    pkg = types.ModuleType("kafka")
    pkg.KafkaAdminClient = KafkaAdminClient
    monkeypatch.setitem(sys.modules, "kafka", pkg)

    backend = KafkaAdminBackend("b1:9092")
    assert backend._impl == "kafka-python"
    assert backend.brokers() == [
        BrokerInfo(1, "h1", 9092, None),  # rack key absent -> None
        BrokerInfo(2, "h2", 9093, "rb"),
    ]
    assert backend.all_topics() == ["events", "logs"]
    assert backend.partition_assignment(["events"]) == {
        "events": {0: [1, 2], 1: [2, 1]}
    }
    backend.close()
    assert closed == [True]


def test_cli_end_to_end_with_fake_kazoo(monkeypatch, capsys):
    # Full stack: run_tool -> open_backend("host:2181") -> ZkBackend -> fake
    # kazoo — the reference's only operating mode, hermetically.
    from kafka_assigner_tpu.cli import run_tool

    znodes = {
        "/brokers/ids": {
            str(b): json.dumps(
                {"host": f"host{b}", "port": 9092, "rack": f"r{b % 3}"}
            )
            for b in range(1, 7)
        },
        "/brokers/topics": {
            "events": json.dumps(
                {"partitions": {str(p): [1 + (p + i) % 5 for i in range(3)]
                                for p in range(6)}}
            ),
        },
    }
    _install_fake_kazoo(monkeypatch, znodes)
    rc = run_tool(["--zk_string", "zkhost:2181", "--mode", "PRINT_REASSIGNMENT",
                   "--solver", "greedy"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "CURRENT ASSIGNMENT:" in out and "NEW ASSIGNMENT:" in out
    from kafka_assigner_tpu.io.json_io import parse_reassignment_json

    new = parse_reassignment_json(out.split("NEW ASSIGNMENT:\n", 1)[1].strip())
    assert set(new["events"]) == set(range(6))


def test_cli_end_to_end_with_fake_confluent(monkeypatch, capsys):
    # kafka:// connect string through the CLI with the stub AdminClient.
    from kafka_assigner_tpu.cli import run_tool

    _install_fake_confluent(monkeypatch)
    rc = run_tool(["--zk_string", "kafka://b1:9092", "--mode",
                   "PRINT_CURRENT_BROKERS"])
    captured = capsys.readouterr()
    assert rc == 0
    header, payload = captured.out.strip().split("\n", 1)
    assert header == "CURRENT BROKERS:"
    assert json.loads(payload)[0]["id"] == 1
    assert "rack" in captured.err  # rack-blind warning reached the operator


def test_cli_refuses_rack_blind_plan_modes(monkeypatch, capsys):
    # VERDICT r3 item 7: a backend that structurally cannot report racks
    # (confluent AdminClient) must not silently produce a rack-unsafe plan;
    # every plan-producing mode refuses with a clear remedy.
    from kafka_assigner_tpu.cli import run_tool

    _install_fake_confluent(monkeypatch)
    for extra in (
        ["--mode", "PRINT_REASSIGNMENT"],
        ["--mode", "RANK_DECOMMISSION"],
        ["--mode", "PRINT_FRESH_ASSIGNMENT", "--topics", "t",
         "--partition_count", "2", "--desired_replication_factor", "1"],
    ):
        rc = run_tool(["--zk_string", "kafka://b1:9092"] + extra)
        captured = capsys.readouterr()
        assert rc == 1, extra
        assert "rack-blind" in captured.err, extra
        assert "ASSIGNMENT" not in captured.out, extra  # no partial plan


def test_cli_rack_blind_plan_allowed_with_explicit_optout(monkeypatch, capsys):
    from kafka_assigner_tpu.cli import run_tool

    _install_fake_confluent(monkeypatch)
    rc = run_tool(["--zk_string", "kafka://b1:9092", "--mode",
                   "PRINT_REASSIGNMENT", "--disable_rack_awareness"])
    captured = capsys.readouterr()
    assert rc == 0
    assert "NEW ASSIGNMENT:" in captured.out


def test_cli_rack_blind_inspection_modes_still_warn(monkeypatch, capsys):
    from kafka_assigner_tpu.cli import run_tool

    _install_fake_confluent(monkeypatch)
    rc = run_tool(["--zk_string", "kafka://b1:9092", "--mode",
                   "PRINT_CURRENT_ASSIGNMENT"])
    captured = capsys.readouterr()
    assert rc == 0
    assert "CURRENT ASSIGNMENT:" in captured.out
    assert "WARNING" in captured.err and "rack" in captured.err


def test_kafka_admin_traffic_lag_gating_and_batching(monkeypatch):
    """ISSUE 11 traffic hook on the AdminClient: supports_traffic() is
    True only when the WHOLE lag chain exists (groups + offsets + an
    end-offset source) — a bare AdminClient must report synthetic
    honestly — and the end-offset fetch is ONE batched call, never a
    per-(group, partition) round trip."""
    import collections
    import sys
    import types

    from kafka_assigner_tpu.io.kafka_admin import KafkaAdminBackend
    from kafka_assigner_tpu.obs.health import synthetic_partition_traffic

    TopicPartition = collections.namedtuple(
        "TopicPartition", ("topic", "partition")
    )
    Meta = collections.namedtuple("Meta", ("offset",))
    end_calls = []

    class BareAdmin:
        def __init__(self, bootstrap_servers):
            pass

        def close(self):
            pass

    class LagAdmin(BareAdmin):
        def list_consumer_groups(self):
            return [("g1", "consumer"), ("g2", "consumer")]

        def list_consumer_group_offsets(self, group):
            committed = {"g1": 90, "g2": 40}[group]
            return {TopicPartition("events", 0): Meta(committed),
                    TopicPartition("events", 9): Meta(5),   # not wanted
                    TopicPartition("events", 1): Meta(-1)}  # never committed

        def end_offsets(self, tps):
            end_calls.append(list(tps))
            return {tp: 100 for tp in tps}

    pkg = types.ModuleType("kafka")
    pkg.KafkaAdminClient = BareAdmin
    pkg.TopicPartition = TopicPartition
    monkeypatch.setitem(sys.modules, "kafka", pkg)

    bare = KafkaAdminBackend("b1:9092")
    assert not bare.supports_traffic()
    wanted = {"events": [0, 1]}
    assert bare.fetch_partition_traffic(wanted) \
        == synthetic_partition_traffic(wanted)

    pkg.KafkaAdminClient = LagAdmin
    lagged = KafkaAdminBackend("b1:9092")
    assert lagged.supports_traffic()
    out = lagged.fetch_partition_traffic(wanted)
    # worst lag across groups: end 100 - min committed 40 = 60
    assert out["events"][0].lag == 60
    # byte rates stay synthetic even when lag is real
    synth = synthetic_partition_traffic(wanted)
    assert out["events"][0].in_bytes == synth["events"][0].in_bytes
    # uncommitted partition keeps its synthetic lag
    assert out["events"][1].lag == synth["events"][1].lag
    # ONE batched end-offset call over the wanted set, not per group/part
    assert len(end_calls) == 1
    assert sorted(end_calls[0]) == [TopicPartition("events", 0),
                                    TopicPartition("events", 1)]


def test_kafka_admin_lag_sweep_failure_degrades_to_synthetic(
    monkeypatch, capsys
):
    import collections
    import sys
    import types

    from kafka_assigner_tpu.io.kafka_admin import KafkaAdminBackend
    from kafka_assigner_tpu.obs.health import synthetic_partition_traffic

    class BrokenLagAdmin:
        def __init__(self, bootstrap_servers):
            pass

        def list_consumer_groups(self):
            raise ConnectionError("coordinator flapping")

        def list_consumer_group_offsets(self, group):
            return {}

        def end_offsets(self, tps):
            return {}

        def close(self):
            pass

    pkg = types.ModuleType("kafka")
    pkg.KafkaAdminClient = BrokenLagAdmin
    pkg.TopicPartition = collections.namedtuple(
        "TopicPartition", ("topic", "partition")
    )
    monkeypatch.setitem(sys.modules, "kafka", pkg)

    backend = KafkaAdminBackend("b1:9092")
    assert backend.supports_traffic()
    wanted = {"t": [0]}
    assert backend.fetch_partition_traffic(wanted) \
        == synthetic_partition_traffic(wanted)
    assert "lag sweep failed" in capsys.readouterr().err
