"""Backend dispatch + znode endpoint parsing + snapshot round-trip."""
from __future__ import annotations

import json

import pytest

from kafka_assigner_tpu.io.base import BrokerInfo, open_backend
from kafka_assigner_tpu.io.snapshot import SnapshotBackend, write_snapshot
from kafka_assigner_tpu.io.zk import _resolve_endpoint


def test_open_backend_dispatch(tmp_path):
    path = tmp_path / "c.json"
    path.write_text(json.dumps({"brokers": [], "topics": {}}))
    assert isinstance(open_backend(f"file://{path}"), SnapshotBackend)
    assert isinstance(open_backend(str(path)), SnapshotBackend)
    # Gated live backends fail with actionable errors when client libs are absent.
    with pytest.raises(RuntimeError, match="kazoo"):
        open_backend("zkhost:2181")
    with pytest.raises(RuntimeError, match="confluent-kafka|kafka-python"):
        open_backend("kafka://broker:9092")


def test_snapshot_round_trip(tmp_path):
    path = str(tmp_path / "c.json")
    brokers = [BrokerInfo(1, "h1", 9092, "a"), BrokerInfo(2, "h2", 9093, None)]
    topics = {"t": {0: [1, 2], 1: [2, 1]}}
    write_snapshot(path, brokers, topics)
    backend = SnapshotBackend(path)
    assert backend.brokers() == brokers
    assert backend.all_topics() == ["t"]
    assert backend.partition_assignment(["t"]) == topics
    with pytest.raises(KeyError, match="not in snapshot"):
        backend.partition_assignment(["missing"])


def test_zk_endpoint_resolution():
    # Plain pre-0.9 znode: top-level host/port.
    assert _resolve_endpoint({"host": "h", "port": 9092}, "1") == ("h", 9092)
    # Multi-listener znode: host null, endpoints list (Kafka >= 0.9).
    meta = {"host": None, "endpoints": ["SSL://secure-host:9093"]}
    assert _resolve_endpoint(meta, "1") == ("secure-host", 9093)
    # IPv6-ish / multiple endpoints: first parseable wins.
    meta = {"host": None, "endpoints": ["PLAINTEXT://h1:9092", "SSL://h1:9093"]}
    assert _resolve_endpoint(meta, "1") == ("h1", 9092)
    # Nothing resolvable: loud failure, never an empty hostname.
    with pytest.raises(ValueError, match="no resolvable host"):
        _resolve_endpoint({"host": None, "endpoints": []}, "7")


def test_cli_validates_solver_before_output(tmp_path, capsys, monkeypatch):
    """--solver must be validated before any metadata read or stdout output."""
    from kafka_assigner_tpu.cli import run_tool
    from kafka_assigner_tpu.solvers import base as solver_base

    path = tmp_path / "c.json"
    path.write_text(json.dumps(
        {"brokers": [{"id": 1, "host": "h", "port": 1}], "topics": {"t": {"0": [1]}}}
    ))

    def broken_get_solver(name):
        raise NotImplementedError("backend unavailable")

    monkeypatch.setattr("kafka_assigner_tpu.cli.get_solver", broken_get_solver)
    with pytest.raises(NotImplementedError):
        run_tool(["--zk_string", str(path), "--mode", "PRINT_REASSIGNMENT"])
    # No partial rollback snapshot was emitted before the failure.
    assert capsys.readouterr().out == ""
