"""Golden byte-parity fixtures: full stdout (banners included) diffed
byte-for-byte against recorded reference-tool output.

The reference jar cannot run in this image (no Maven deps, no JVM network),
so the fixtures are *derived* recordings, hand-computed from the reference's
two serializers and pinned as files under ``tests/golden/``:

- "CURRENT ASSIGNMENT" sections: Kafka 0.10's
  ``zkUtils.formatAsReassignmentJson`` → ``kafka.utils.Json.encode``, which
  walks small Scala immutable Maps in insertion order
  (``{"version":…,"partitions":…}``, ``{"topic":…,"partition":…,
  "replicas":…}``), compact, raw strings.
- "NEW ASSIGNMENT" / "CURRENT BROKERS" sections: org.json 20131018
  ``toString()`` (``KafkaAssignmentGenerator.java:113-129,169-186``), which
  walks ``java.util.HashMap`` bucket order — on JDK8 that is
  ``partitions,version`` / ``partition,replicas,topic`` /
  ``[rack,]port,host,id`` (derivation in ``io/json_io.py``; JDK7 buckets
  differently, so the reference's own bytes are JVM-dependent and we pin the
  JDK8 order).
- Replica contents in ``mode3_steady_state.txt`` are hand-traced through the
  reference greedy: sticky fill keeps the steady-state assignment
  (``KafkaAssignmentStrategy.java:101-131``) and leadership rotation for
  topic "x" (``abs(hash)=120``) starts at index 0. The richer
  ``mode3_replacement.txt`` replica lists come from the bit-faithful greedy
  oracle (``solvers/greedy.py``, differential-tested against the Java
  semantics in ``test_strategy_scenarios.py`` / ``test_greedy_semantics.py``).

Known divergence, on purpose: in the reference, the *entry order* of mode 1's
partitions array is the iteration order of a ``scala.collection.mutable
.HashMap[TopicAndPartition, _]`` (``ZkUtils.getReplicaAssignmentForTopics``)
— arbitrary and unstable across Scala versions. We emit topics in request
order with partitions ascending instead; fixtures use assignments where that
order is well-defined or singleton. See PARITY.md.
"""
from __future__ import annotations

import json
import os

import pytest

from kafka_assigner_tpu.cli import run_tool

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")


def golden(name: str) -> str:
    with open(os.path.join(GOLDEN_DIR, name), "r", encoding="utf-8") as f:
        return f.read()


@pytest.fixture()
def steady_snapshot(tmp_path):
    """1 topic x 1 partition x RF=2 over 2 rackless brokers: every byte of
    modes 1 and 3 is hand-derivable (sticky keeps all; rotation start 0)."""
    cluster = {
        "brokers": [
            {"id": 1, "host": "h1", "port": 9092},
            {"id": 2, "host": "h2", "port": 9092},
        ],
        "topics": {"x": {"0": [1, 2]}},
    }
    path = tmp_path / "steady.json"
    path.write_text(json.dumps(cluster))
    return str(path)


@pytest.fixture()
def replacement_snapshot(tmp_path):
    """Broker 3 replaced by 4 (racks a/b/c): canonical replacement run."""
    cluster = {
        "brokers": [
            {"id": 1, "host": "h1", "port": 9092, "rack": "a"},
            {"id": 2, "host": "h2", "port": 9092, "rack": "b"},
            {"id": 4, "host": "h4", "port": 9092, "rack": "c"},
        ],
        "topics": {
            "events": {
                str(p): [1 + (p + i) % 3 for i in range(2)] for p in range(4)
            },
            "logs": {
                str(p): [1 + (p + i) % 3 for i in range(2)] for p in range(2)
            },
        },
    }
    path = tmp_path / "replacement.json"
    path.write_text(json.dumps(cluster))
    return str(path)


def _stdout(capsys, *argv) -> str:
    rc = run_tool(list(argv))
    out = capsys.readouterr().out
    assert rc == 0, out
    return out


def test_golden_mode1_current_assignment(capsys, steady_snapshot):
    out = _stdout(
        capsys, "--zk_string", steady_snapshot,
        "--mode", "PRINT_CURRENT_ASSIGNMENT",
    )
    assert out == golden("mode1_single_partition.txt")


def test_golden_mode2_brokers(capsys, tmp_path):
    cluster = {
        "brokers": [
            {"id": 1, "host": "h1", "port": 9092, "rack": "a"},
            {"id": 2, "host": "h2", "port": 9092},
        ],
        "topics": {},
    }
    path = tmp_path / "brokers.json"
    path.write_text(json.dumps(cluster))
    out = _stdout(
        capsys, "--zk_string", str(path), "--mode", "PRINT_CURRENT_BROKERS"
    )
    assert out == golden("mode2_brokers.txt")


@pytest.mark.parametrize("solver", ["greedy", "tpu"])
def test_golden_mode3_steady_state(capsys, steady_snapshot, solver):
    out = _stdout(
        capsys, "--zk_string", steady_snapshot,
        "--mode", "PRINT_REASSIGNMENT", "--solver", solver,
    )
    assert out == golden("mode3_steady_state.txt")


def test_golden_mode3_replacement(capsys, replacement_snapshot):
    out = _stdout(
        capsys, "--zk_string", replacement_snapshot,
        "--mode", "PRINT_REASSIGNMENT", "--solver", "greedy",
    )
    assert out == golden("mode3_replacement.txt")


# ---------------------------------------------------------------------------
# Mechanical JDK8 bucket-order derivation (VERDICT round 2 #7). The three key
# orders pinned above were originally hand-derived; this simulator re-derives
# them from first principles (String.hashCode -> JDK8 hash spread -> HashMap
# table walk) so a transcription mistake in io/json_io.py cannot survive.
# ---------------------------------------------------------------------------

from kafka_assigner_tpu.utils.javahash import java_string_hash  # noqa: E402


def _jdk8_hashmap_order(keys, initial_capacity=16):
    """Iteration order of a JDK8 ``java.util.HashMap`` holding ``keys``.

    Models exactly what org.json 20131018's ``JSONObject.toString()`` walks
    (its backing map is ``new HashMap<String, Object>()``, default capacity
    16, load factor 0.75):

    - per-key slot: ``(cap - 1) & (h ^ (h >>> 16))`` over ``String.hashCode``
      (``HashMap.hash``/``putVal``, JDK8);
    - iteration: table slots ascending, chains within a slot in insertion
      order (``HashMap.HashIterator``);
    - resize at ``size > 0.75 * cap`` doubles the table; JDK8's lo/hi split
      preserves relative chain order, equivalent to re-bucketing every key at
      the doubled capacity in iteration order.

    Not modeled: bin treeification (needs an 8-chain — unreachable for the
    tool's <=4-key objects and vanishingly unlikely below ~64 keys).
    """

    def slot(key, cap):
        h = java_string_hash(key) & 0xFFFFFFFF
        return (h ^ (h >> 16)) & (cap - 1)

    cap = initial_capacity
    table = [[] for _ in range(cap)]
    size = 0
    for k in keys:
        table[slot(k, cap)].append(k)
        size += 1
        if size > (cap * 3) // 4:
            cap *= 2
            doubled = [[] for _ in range(cap)]
            for chain in table:
                for kk in chain:
                    doubled[slot(kk, cap)].append(kk)
            table = doubled
    return [k for chain in table for k in chain]


def test_bucket_order_simulator_derives_the_pinned_orders():
    # The three object shapes the reference hand-builds with org.json
    # (KafkaAssignmentGenerator.java:113-129,169-186), keys in the
    # reference's put() order.
    assert _jdk8_hashmap_order(["version", "partitions"]) == [
        "partitions", "version"]
    assert _jdk8_hashmap_order(["topic", "partition", "replicas"]) == [
        "partition", "replicas", "topic"]
    assert _jdk8_hashmap_order(["id", "host", "port", "rack"]) == [
        "rack", "port", "host", "id"]
    assert _jdk8_hashmap_order(["id", "host", "port"]) == [
        "port", "host", "id"]
    # Below the resize threshold, bucket order is insertion-order independent
    # — the property the pinned fixtures silently rely on.
    for keys in (["version", "partitions"], ["topic", "partition", "replicas"],
                 ["id", "host", "port", "rack"]):
        assert _jdk8_hashmap_order(list(reversed(keys))) == \
            _jdk8_hashmap_order(keys)


def test_formatters_match_simulator_derived_bytes():
    """Byte-build the expected JSON purely from the simulator's key order and
    diff against the formatters — io/json_io.py's hard-coded literal orders
    can no longer drift from the derivation."""
    from kafka_assigner_tpu.io.base import BrokerInfo
    from kafka_assigner_tpu.io.json_io import (
        format_brokers_json,
        format_reassignment_pairs,
    )

    pairs = [("events", {1: [2, 1], 0: [1, 2]}), ("logs", {0: [2]})]
    entry_keys = _jdk8_hashmap_order(["topic", "partition", "replicas"])
    top_keys = _jdk8_hashmap_order(["version", "partitions"])

    def entry_json(topic, partition, replicas):
        f = {"topic": json.dumps(topic), "partition": str(partition),
             "replicas": json.dumps(replicas, separators=(",", ":"))}
        return "{" + ",".join(f'"{k}":{f[k]}' for k in entry_keys) + "}"

    entries = ",".join(
        entry_json(t, p, a[p]) for t, a in pairs for p in sorted(a)
    )
    f = {"version": "1", "partitions": "[" + entries + "]"}
    expected = "{" + ",".join(f'"{k}":{f[k]}' for k in top_keys) + "}"
    assert format_reassignment_pairs(pairs) == expected

    brokers = [BrokerInfo(7, "h7", 9092, "ra"), BrokerInfo(8, "h8", 9093, None)]
    def broker_json(b):
        keys = ["id", "host", "port"] + (["rack"] if b.rack is not None else [])
        f = {"id": str(b.id), "host": json.dumps(b.host), "port": str(b.port),
             "rack": json.dumps(b.rack)}
        return "{" + ",".join(
            f'"{k}":{f[k]}' for k in _jdk8_hashmap_order(keys)) + "}"
    assert format_brokers_json(brokers) == \
        "[" + ",".join(broker_json(b) for b in brokers) + "]"


def test_bucket_order_simulator_resize_regime():
    """>16-key objects (VERDICT round 2 #7): the tool's own output never
    builds one (max 4 keys per org.json object), but the simulator must stay
    trustworthy past the 12-key resize threshold in case a future mode does.
    JDK8's order-preserving lo/hi split means inserting through a resize is
    equivalent to bucketing everything at the doubled capacity directly —
    pin that equivalence, plus permutation-completeness."""
    keys = [f"k{i}" for i in range(20)]           # 20 > 12 -> one resize
    through_resize = _jdk8_hashmap_order(keys, initial_capacity=16)
    direct_at_32 = _jdk8_hashmap_order(keys, initial_capacity=32)
    assert through_resize == direct_at_32
    assert sorted(through_resize) == sorted(keys)
    # Multi-key chains keep insertion order: craft two keys sharing a slot.
    by_slot = {}
    for k in keys:
        h = java_string_hash(k) & 0xFFFFFFFF
        by_slot.setdefault((h ^ (h >> 16)) & 31, []).append(k)
    for chain in by_slot.values():
        if len(chain) > 1:
            order = _jdk8_hashmap_order(keys, initial_capacity=32)
            assert [k for k in order if k in chain] == chain


@pytest.fixture()
def multitopic_snapshot(tmp_path):
    """18 topics x 2 partitions, RF=2, 4 rackless brokers — the multi-topic
    mode-3 shape where emission order (CLI request order x ascending
    partitions) and cross-topic leadership context actually matter."""
    topics = {f"t{i:02d}": {str(p): [1 + (i + p) % 4, 1 + (i + p + 1) % 4]
                            for p in range(2)} for i in range(18)}
    cluster = {"brokers": [{"id": b, "host": f"h{b}", "port": 9092}
                           for b in range(1, 5)], "topics": topics}
    path = tmp_path / "multi.json"
    path.write_text(json.dumps(cluster))
    return str(path)


# Deliberately unsorted: the NEW ASSIGNMENT array must follow CLI request
# order (reference topic loop, KafkaAssignmentGenerator.java:173-183), not
# lexicographic order; a sorted fixture could not tell the two apart.
MULTITOPIC_ORDER = ",".join(
    f"t{i:02d}" for i in (17, 3, 0, 11, 5, 16, 8, 2, 14, 9, 1, 13, 7, 4, 15, 10, 6, 12)
)


@pytest.mark.parametrize("solver", ["greedy", "tpu"])
def test_golden_mode3_multitopic(capsys, multitopic_snapshot, solver):
    out = _stdout(
        capsys, "--zk_string", multitopic_snapshot,
        "--mode", "PRINT_REASSIGNMENT", "--solver", solver,
        "--topics", MULTITOPIC_ORDER,
    )
    assert out == golden("mode3_multitopic.txt")
