"""Golden byte-parity fixtures: full stdout (banners included) diffed
byte-for-byte against recorded reference-tool output.

The reference jar cannot run in this image (no Maven deps, no JVM network),
so the fixtures are *derived* recordings, hand-computed from the reference's
two serializers and pinned as files under ``tests/golden/``:

- "CURRENT ASSIGNMENT" sections: Kafka 0.10's
  ``zkUtils.formatAsReassignmentJson`` → ``kafka.utils.Json.encode``, which
  walks small Scala immutable Maps in insertion order
  (``{"version":…,"partitions":…}``, ``{"topic":…,"partition":…,
  "replicas":…}``), compact, raw strings.
- "NEW ASSIGNMENT" / "CURRENT BROKERS" sections: org.json 20131018
  ``toString()`` (``KafkaAssignmentGenerator.java:113-129,169-186``), which
  walks ``java.util.HashMap`` bucket order — on JDK8 that is
  ``partitions,version`` / ``partition,replicas,topic`` /
  ``[rack,]port,host,id`` (derivation in ``io/json_io.py``; JDK7 buckets
  differently, so the reference's own bytes are JVM-dependent and we pin the
  JDK8 order).
- Replica contents in ``mode3_steady_state.txt`` are hand-traced through the
  reference greedy: sticky fill keeps the steady-state assignment
  (``KafkaAssignmentStrategy.java:101-131``) and leadership rotation for
  topic "x" (``abs(hash)=120``) starts at index 0. The richer
  ``mode3_replacement.txt`` replica lists come from the bit-faithful greedy
  oracle (``solvers/greedy.py``, differential-tested against the Java
  semantics in ``test_strategy_scenarios.py`` / ``test_greedy_semantics.py``).

Known divergence, on purpose: in the reference, the *entry order* of mode 1's
partitions array is the iteration order of a ``scala.collection.mutable
.HashMap[TopicAndPartition, _]`` (``ZkUtils.getReplicaAssignmentForTopics``)
— arbitrary and unstable across Scala versions. We emit topics in request
order with partitions ascending instead; fixtures use assignments where that
order is well-defined or singleton. See PARITY.md.
"""
from __future__ import annotations

import json
import os

import pytest

from kafka_assigner_tpu.cli import run_tool

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")


def golden(name: str) -> str:
    with open(os.path.join(GOLDEN_DIR, name), "r", encoding="utf-8") as f:
        return f.read()


@pytest.fixture()
def steady_snapshot(tmp_path):
    """1 topic x 1 partition x RF=2 over 2 rackless brokers: every byte of
    modes 1 and 3 is hand-derivable (sticky keeps all; rotation start 0)."""
    cluster = {
        "brokers": [
            {"id": 1, "host": "h1", "port": 9092},
            {"id": 2, "host": "h2", "port": 9092},
        ],
        "topics": {"x": {"0": [1, 2]}},
    }
    path = tmp_path / "steady.json"
    path.write_text(json.dumps(cluster))
    return str(path)


@pytest.fixture()
def replacement_snapshot(tmp_path):
    """Broker 3 replaced by 4 (racks a/b/c): canonical replacement run."""
    cluster = {
        "brokers": [
            {"id": 1, "host": "h1", "port": 9092, "rack": "a"},
            {"id": 2, "host": "h2", "port": 9092, "rack": "b"},
            {"id": 4, "host": "h4", "port": 9092, "rack": "c"},
        ],
        "topics": {
            "events": {
                str(p): [1 + (p + i) % 3 for i in range(2)] for p in range(4)
            },
            "logs": {
                str(p): [1 + (p + i) % 3 for i in range(2)] for p in range(2)
            },
        },
    }
    path = tmp_path / "replacement.json"
    path.write_text(json.dumps(cluster))
    return str(path)


def _stdout(capsys, *argv) -> str:
    rc = run_tool(list(argv))
    out = capsys.readouterr().out
    assert rc == 0, out
    return out


def test_golden_mode1_current_assignment(capsys, steady_snapshot):
    out = _stdout(
        capsys, "--zk_string", steady_snapshot,
        "--mode", "PRINT_CURRENT_ASSIGNMENT",
    )
    assert out == golden("mode1_single_partition.txt")


def test_golden_mode2_brokers(capsys, tmp_path):
    cluster = {
        "brokers": [
            {"id": 1, "host": "h1", "port": 9092, "rack": "a"},
            {"id": 2, "host": "h2", "port": 9092},
        ],
        "topics": {},
    }
    path = tmp_path / "brokers.json"
    path.write_text(json.dumps(cluster))
    out = _stdout(
        capsys, "--zk_string", str(path), "--mode", "PRINT_CURRENT_BROKERS"
    )
    assert out == golden("mode2_brokers.txt")


@pytest.mark.parametrize("solver", ["greedy", "tpu"])
def test_golden_mode3_steady_state(capsys, steady_snapshot, solver):
    out = _stdout(
        capsys, "--zk_string", steady_snapshot,
        "--mode", "PRINT_REASSIGNMENT", "--solver", solver,
    )
    assert out == golden("mode3_steady_state.txt")


def test_golden_mode3_replacement(capsys, replacement_snapshot):
    out = _stdout(
        capsys, "--zk_string", replacement_snapshot,
        "--mode", "PRINT_REASSIGNMENT", "--solver", "greedy",
    )
    assert out == golden("mode3_replacement.txt")
