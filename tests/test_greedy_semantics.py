"""Pin the greedy oracle to the reference's *exact* semantics: Java hashCode,
rotated node-processing order, deterministic output, cross-topic leadership
counters, and the documented RF-decrease quirk."""
from __future__ import annotations


from kafka_assigner_tpu.assigner import TopicAssigner
from kafka_assigner_tpu.solvers.greedy import node_processing_order
from kafka_assigner_tpu.utils.javahash import java_string_hash, topic_start_index


def test_java_string_hash_known_values():
    # Values computed by the JVM's String.hashCode.
    assert java_string_hash("") == 0
    assert java_string_hash("test") == 3556498
    assert java_string_hash("a") == 97
    # 32-bit wraparound on longer strings (negative JVM hashes).
    assert java_string_hash("kafka-assigner") == -1652112221
    assert java_string_hash("the-quick-brown-fox-jumps-over") == -617901171
    assert java_string_hash("__consumer_offsets") == -970371369


def test_topic_start_index_negative_hash():
    # Math.abs of a negative hash, then modulo (KafkaAssignmentStrategy.java:190).
    h = java_string_hash("kafka-assigner")
    assert h < 0
    assert topic_start_index("kafka-assigner", 7) == abs(h) % 7


def test_node_processing_order_rotation():
    # "test".hashCode() == 3556498; 3556498 % 5 == 3, so ascending ids are laid
    # out starting at slot 3 with wraparound (KafkaAssignmentStrategy.java:188-200).
    assert node_processing_order("test", [10, 11, 12, 13, 14]) == [12, 13, 14, 10, 11]
    assert node_processing_order("test", [1]) == [1]


def test_determinism():
    current = {p: [(p + i) % 7 + 10 for i in range(3)] for p in range(20)}
    brokers = set(range(10, 19))
    racks = {b: f"r{b % 3}" for b in brokers}
    a1 = TopicAssigner("greedy").generate_assignment("t", current, brokers, racks, -1)
    a2 = TopicAssigner("greedy").generate_assignment("t", current, brokers, racks, -1)
    assert a1 == a2


def test_cross_topic_context_balances_leaders():
    # The Context persists across topics through one assigner
    # (KafkaTopicAssigner.java:19-23): leaders must spread across brokers
    # rather than repeating one favorite.
    assigner = TopicAssigner("greedy")
    brokers = {10, 11, 12}
    leaders = []
    for t in ("alpha", "beta", "gamma"):
        current = {0: [10, 11, 12]}
        new = assigner.generate_assignment(t, current, brokers, {}, -1)
        leaders.append(new[0][0])
    # Three solves of the same replica set: each broker leads exactly once.
    assert sorted(leaders) == [10, 11, 12]


def test_rf_decrease_quirk_preserved():
    # Reference behavior: sticky fill has no per-partition limit
    # (KafkaAssignmentStrategy.java:320-324), so lowering RF can leave
    # partitions with more replicas than requested. Bug-compatible on purpose.
    current = {0: [10, 11, 12], 1: [11, 12, 13], 2: [12, 13, 10], 3: [13, 10, 11]}
    brokers = {10, 11, 12, 13}
    new = TopicAssigner("greedy").generate_assignment("test", current, brokers, {}, 2)
    sizes = sorted(len(r) for r in new.values())
    # cap = ceil(4*2/4) = 2 limits totals to 8, but individual partitions may
    # keep up to 3 sticky replicas.
    assert sum(sizes) <= 8
    assert max(sizes) >= 2


def test_sticky_round_robin_capacity_order():
    # Round-robin sticky fill: slot 0 of every partition is offered before any
    # slot 1 (KafkaAssignmentStrategy.java:101-131). With capacity 1 per node,
    # each node keeps the partition whose *leader* it was, not a follower.
    current = {0: [10, 11], 1: [11, 10]}
    brokers = {10, 11, 12, 13}
    new = TopicAssigner("greedy").generate_assignment("t", current, brokers, {}, -1)
    # cap = ceil(2*2/4)=1: node 10 keeps p0 (leader slot), node 11 keeps p1.
    assert 10 in new[0] and 11 in new[1]
    assert 10 not in new[1] and 11 not in new[0]
