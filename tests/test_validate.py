"""Feasibility pre-validation tests (SURVEY.md §5 failure-detection build
item: catch infeasible solves before the solver's mid-run hard error)."""
from __future__ import annotations

from kafka_assigner_tpu.validate import (
    validate_cluster_feasibility,
    validate_topic_feasibility,
)


def test_rf_exceeds_racks_is_error():
    issues = validate_topic_feasibility(
        "t", 4, 3, {1, 2, 3}, {1: "a", 2: "a", 3: "b"}
    )
    assert [i.severity for i in issues] == ["error"]
    assert "exceeds rack count" in issues[0].message


def test_rackless_nodes_count_as_own_racks():
    # No rack map: every node is its own rack, so RF <= N is always rack-feasible.
    issues = validate_topic_feasibility("t", 4, 3, {1, 2, 3, 4}, {})
    assert all(i.severity != "error" for i in issues)


def test_uneven_racks_with_rf_equal_racks():
    # 2 racks of sizes 1 and 3, RF=2: every partition needs both racks; the
    # singleton rack can hold at most cap partitions.
    brokers = {1, 2, 3, 4}
    racks = {1: "a", 2: "b", 3: "b", 4: "b"}
    issues = validate_topic_feasibility("t", 10, 2, brokers, racks)
    assert any(i.severity == "error" for i in issues)


def test_feasible_balanced_cluster_is_clean_or_warning_only():
    brokers = set(range(12))
    racks = {b: f"r{b % 4}" for b in brokers}
    issues = validate_topic_feasibility("t", 12, 3, brokers, racks)
    assert all(i.severity == "warning" for i in issues)


def test_cluster_validation_infers_rf():
    topics = [("t", {0: [1, 2], 1: [2, 1]})]
    issues = validate_cluster_feasibility(topics, {1, 2, 3}, {1: "a", 2: "a", 3: "a"})
    assert issues and issues[0].severity == "error"


def test_nonuniform_rf_topic_reported_not_raised():
    # ADVICE round 1: RF inference must not silently adopt an arbitrary
    # partition's RF; validation reports the uniformity violation as an issue.
    brokers = {1, 2, 3}
    issues = validate_cluster_feasibility(
        [("bad", {0: [1, 2], 1: [1, 2, 3]}), ("good", {0: [1, 2], 1: [2, 3]})],
        brokers,
        {},
    )
    assert any(
        i.topic == "bad" and i.severity == "error"
        and "unexpected replication factor" in i.message
        for i in issues
    )
    assert not any(i.topic == "good" and i.severity == "error" for i in issues)
