"""The fault-injection harness and the resilience layer it drives (ISSUE 5):
schedule determinism, session re-establishment with idempotent read replay
(byte-identical results), graceful topic-level degradation, the solver
fallback chain, and the documented CLI exit codes."""
from __future__ import annotations

import json

import pytest

from kafka_assigner_tpu import faults
from kafka_assigner_tpu.cli import (
    EXIT_DEGRADED,
    EXIT_INGEST,
    EXIT_SOLVE,
    EXIT_VALIDATION,
    run,
)
from kafka_assigner_tpu.faults.inject import (
    FaultEvent,
    FaultInjector,
    FaultSpecError,
    InjectedResyncStall,
    parse_spec,
    random_schedule,
)
from kafka_assigner_tpu.io.zkwire import (
    MiniZkClient,
    NoNodeError,
    ZkConnectionError,
    ZkWireError,
)
from kafka_assigner_tpu.obs import run_capture

from .jute_server import JuteZkServer, cluster_tree


@pytest.fixture(autouse=True)
def _fresh_injector():
    """Each test starts with no installed injector and a cold env cache —
    the cache is keyed by (spec, seed) and would otherwise leak consumed
    per-scope counters across tests."""
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def zk_server():
    server = JuteZkServer(cluster_tree())
    server.start()
    yield server
    server.shutdown()


@pytest.fixture()
def snapshot(tmp_path):
    cluster = {
        "brokers": [
            {"id": 100 + i, "host": f"host{i}", "port": 9092,
             "rack": f"r{i % 3}"}
            for i in range(6)
        ],
        "topics": {
            "events": {
                str(p): [100 + (p + i) % 5 for i in range(3)]
                for p in range(6)
            },
            "logs": {
                str(p): [100 + (p + i) % 5 for i in range(2)]
                for p in range(4)
            },
        },
    }
    path = tmp_path / "cluster.json"
    path.write_text(json.dumps(cluster))
    return str(path)


# --- spec / schedule ---------------------------------------------------------

def test_parse_spec_explicit_events():
    events = parse_spec(
        "reply:3=drop; reply:5=trunc:8 ;connect:0=blackhole;"
        "handshake:1=expire;solve=crash;reply:2=slow:0.01"
    )
    assert FaultEvent("reply", 3, "drop") in events
    assert FaultEvent("reply", 5, "trunc", 8.0) in events
    assert FaultEvent("connect", 0, "blackhole") in events
    assert FaultEvent("handshake", 1, "expire") in events
    assert FaultEvent("solve", 0, "crash") in events  # index defaults to 0
    assert FaultEvent("reply", 2, "slow", 0.01) in events


@pytest.mark.parametrize("bad", [
    "reply:3",                # no kind
    "nowhere:0=drop",         # unknown scope
    "reply:0=expire",         # kind not valid for scope
    "reply:x=drop",           # non-integer index
    "reply:-1=drop",          # negative index
    "reply:0=slow:abc",       # non-numeric arg
])
def test_parse_spec_rejects_malformed(bad):
    with pytest.raises(FaultSpecError):
        parse_spec(bad)


def test_random_schedule_is_seed_deterministic():
    a = random_schedule(seed=7, rate=0.3)
    b = random_schedule(seed=7, rate=0.3)
    c = random_schedule(seed=8, rate=0.3)
    assert a == b
    assert a != c
    assert a  # rate 0.3 over ~70 slots: statistically certain to fire


def test_malformed_spec_env_is_ignored_loudly(monkeypatch, capsys):
    monkeypatch.setenv("KA_FAULTS_SPEC", "reply:0=warp")
    assert faults.active_injector() is None
    assert "ignoring malformed KA_FAULTS_SPEC" in capsys.readouterr().err


def test_env_injector_cached_per_spec(monkeypatch):
    monkeypatch.setenv("KA_FAULTS_SPEC", "reply:0=slow:0.001")
    first = faults.active_injector()
    assert first is not None and faults.active_injector() is first


# --- wire-client resilience (session replay) ---------------------------------

PATHS = [f"/brokers/ids/{i}" for i in (1, 2, 3, 4)] + [
    "/brokers/topics/events", "/brokers/topics/logs"
]


def _client(server, **kw):
    return MiniZkClient(f"127.0.0.1:{server.port}", timeout=5.0, **kw)


def _baseline(server):
    client = _client(server)
    client.start()
    try:
        return client.get_many(PATHS)
    finally:
        client.stop()
        client.close()


@pytest.mark.parametrize("spec", [
    "reply:2=drop",                   # socket drop mid-frame
    "reply:1=trunc",                  # truncated reply desyncs the decoder
    "reply:0=trunc:3",                # truncated INSIDE the reply header
    "reply:0=slow:0.01",              # slow reply: no failure at all
    "reply:1=drop;reply:4=drop",      # two drops in one batch
])
def test_pipelined_reads_self_heal_byte_identical(zk_server, spec):
    expected = _baseline(zk_server)
    faults.install(FaultInjector(parse_spec(spec)))
    with run_capture() as rec:
        client = _client(zk_server)
        client.start()
        try:
            assert client.get_many(PATHS) == expected
        finally:
            client.stop()
            client.close()
    n_faults = len(spec.split(";"))
    assert rec.counters.get("faults.injected") == n_faults
    if "drop" in spec or "trunc" in spec:
        assert rec.counters.get("zk.session.reestablished", 0) >= 1


def test_serial_reads_self_heal(zk_server):
    expected = _baseline(zk_server)
    faults.install(FaultInjector(parse_spec("reply:1=drop")))
    client = _client(zk_server)
    client.start()
    try:
        assert [client.get(p) for p in PATHS] == expected
        # The listing op heals too.
        assert client.get_children("/brokers/ids") == ["1", "2", "3", "4"]
    finally:
        client.stop()
        client.close()


def test_session_retries_zero_fails_fast(zk_server, monkeypatch):
    monkeypatch.setenv("KA_ZK_SESSION_RETRIES", "0")
    faults.install(FaultInjector(parse_spec("reply:1=drop")))
    client = _client(zk_server)
    client.start()
    try:
        with pytest.raises((OSError, ZkConnectionError)):
            client.get_many(PATHS)
    finally:
        client.close()


def test_nonode_race_strict_raises_in_order(zk_server):
    # An injected NoNode on the reply stream is indistinguishable from a
    # znode deleted mid-scan; strict pipelining raises it at the victim's
    # position and the session stays usable.
    faults.install(FaultInjector(parse_spec("reply:1=nonode")))
    client = _client(zk_server)
    client.start()
    try:
        with pytest.raises(NoNodeError, match="/brokers/ids/2"):
            client.get_many(PATHS)
        assert client.get_children("/brokers/topics") == ["events", "logs"]
    finally:
        client.stop()
        client.close()


def test_nonode_race_missing_ok_yields_none(zk_server):
    expected = _baseline(zk_server)
    faults.install(FaultInjector(parse_spec("reply:1=nonode")))
    client = _client(zk_server)
    client.start()
    try:
        got = client.get_many(PATHS, missing_ok=True)
        assert got[1] is None  # the victim's position, not an exception
        assert got[:1] == expected[:1] and got[2:] == expected[2:]
    finally:
        client.stop()
        client.close()


def test_connect_blackhole_consumes_one_attempt(zk_server, monkeypatch):
    monkeypatch.setenv("KA_ZK_CONNECT_RETRIES", "3")
    faults.install(FaultInjector(parse_spec("connect:0=blackhole")))
    client = _client(zk_server)
    client.start()  # first attempt refused, retry lands
    try:
        assert client.get_children("/brokers/topics") == ["events", "logs"]
    finally:
        client.stop()
        client.close()


def test_connect_blackhole_everywhere_reports_failure(zk_server, monkeypatch):
    monkeypatch.setenv("KA_ZK_CONNECT_RETRIES", "2")
    faults.install(FaultInjector(parse_spec(
        ";".join(f"connect:{i}=blackhole" for i in range(8))
    )))
    client = _client(zk_server)
    with pytest.raises(ZkWireError, match=r"after 2 pass\(es\)"):
        client.start()


def test_injected_handshake_expiry_is_survivable(zk_server, monkeypatch):
    # Client-side twin of the server-side expiry test: the injected expired
    # ConnectResponse drives the same parsing branch, and the connect-pass
    # loop recovers.
    monkeypatch.setenv("KA_ZK_CONNECT_RETRIES", "3")
    faults.install(FaultInjector(parse_spec("handshake:0=expire")))
    client = _client(zk_server)
    client.start()
    try:
        assert client.get_children("/brokers/topics") == ["events", "logs"]
    finally:
        client.stop()
        client.close()


# --- graceful degradation / fallback chain -----------------------------------

def test_stream_best_effort_skips_vanished_topic(snapshot):
    from kafka_assigner_tpu.generator import stream_initial_assignment
    from kafka_assigner_tpu.io.snapshot import SnapshotBackend

    backend = SnapshotBackend(snapshot)
    skipped: list = []
    initial, pre = stream_initial_assignment(
        backend, ["events", "ghost", "logs"],
        failure_policy="best-effort", skipped=skipped,
    )
    assert skipped == ["ghost"]
    assert set(initial) == {"events", "logs"}
    # Strict keeps the fail-fast contract.
    with pytest.raises(KeyError, match="ghost"):
        stream_initial_assignment(backend, ["events", "ghost"])


def test_assigner_falls_back_to_greedy_per_group():
    from kafka_assigner_tpu.assigner import TopicAssigner
    from kafka_assigner_tpu.solvers.greedy import GreedySolver

    class Crashy(GreedySolver):
        name = "crashy"

        def assign(self, *a, **kw):
            raise RuntimeError("device OOM")

    topics = {
        "a": {0: [1, 2], 1: [2, 3]},
        "b": {0: [3, 1]},
    }
    brokers = {1, 2, 3}
    oracle = TopicAssigner(solver="greedy").generate_assignments(
        list(topics.items()), brokers, {}, -1
    )
    best = TopicAssigner(solver=Crashy(), failure_policy="best-effort")
    got = best.generate_assignments(list(topics.items()), brokers, {}, -1)
    assert got == oracle  # parity: the fallback output IS the greedy output
    assert best.fallbacks == 2  # one per crashed serial group

    strict = TopicAssigner(solver=Crashy())
    with pytest.raises(RuntimeError, match="device OOM"):
        strict.generate_assignments(list(topics.items()), brokers, {}, -1)
    # ValueError (validation/infeasibility) never triggers the fallback.

    class Infeasible(GreedySolver):
        name = "infeasible"

        def assign(self, *a, **kw):
            raise ValueError("Partition 0 could not be fully assigned!")

    nofb = TopicAssigner(solver=Infeasible(), failure_policy="best-effort")
    with pytest.raises(ValueError, match="fully assigned"):
        nofb.generate_assignments(list(topics.items()), brokers, {}, -1)
    assert nofb.fallbacks == 0


# --- CLI exit codes ----------------------------------------------------------

def _dead_port() -> int:
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_exit_code_ingest_failure(monkeypatch, capsys):
    monkeypatch.setenv("KA_ZK_CONNECT_RETRIES", "1")
    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    rc = run([
        "--zk_string", f"127.0.0.1:{_dead_port()}",
        "--mode", "PRINT_REASSIGNMENT",
    ])
    err = capsys.readouterr().err
    assert rc == EXIT_INGEST
    assert "metadata ingest failed" in err


def test_exit_code_validation_failure(snapshot, capsys):
    rc = run([
        "--zk_string", snapshot, "--mode", "PRINT_REASSIGNMENT",
        "--desired_replication_factor", "99",
    ])
    err = capsys.readouterr().err
    assert rc == EXIT_VALIDATION
    assert "higher replication factor" in err


def test_exit_code_solve_failure_strict(snapshot, monkeypatch, capsys):
    monkeypatch.setenv("KA_FAULTS_SPEC", "solve:0=crash")
    rc = run([
        "--zk_string", snapshot, "--mode", "PRINT_REASSIGNMENT",
        "--solver", "tpu",
    ])
    err = capsys.readouterr().err
    assert rc == EXIT_SOLVE
    assert "fault injected: solve" in err


def test_exit_code_degraded_solver_fallback(
    snapshot, monkeypatch, capsys, tmp_path
):
    # Greedy baseline: the fallback output must be byte-identical to it
    # (all backends are parity-pinned), with the degraded exit code and the
    # fallback accounted in the run report.
    assert run([
        "--zk_string", snapshot, "--mode", "PRINT_REASSIGNMENT",
        "--solver", "greedy",
    ]) == 0
    baseline = capsys.readouterr().out

    report_path = tmp_path / "report.json"
    monkeypatch.setenv("KA_FAULTS_SPEC", "solve:0=crash")
    rc = run([
        "--zk_string", snapshot, "--mode", "PRINT_REASSIGNMENT",
        "--solver", "tpu", "--failure-policy", "best-effort",
        "--report-json", str(report_path),
    ])
    captured = capsys.readouterr()
    assert rc == EXIT_DEGRADED
    assert captured.out == baseline
    assert "falling back to the greedy solver" in captured.err
    report = json.loads(report_path.read_text())
    assert report["status"] == "degraded"
    assert report["metrics"]["counters"]["solve.fallbacks"] == 1
    assert report["metrics"]["counters"]["faults.injected"] == 1


def test_exit_code_degraded_skipped_topic(snapshot, monkeypatch, capsys, tmp_path):
    report_path = tmp_path / "report.json"
    monkeypatch.setenv("KA_FAILURE_POLICY", "best-effort")  # knob, not flag
    rc = run([
        "--zk_string", snapshot, "--mode", "PRINT_REASSIGNMENT",
        "--topics", "events,ghost,logs",
        "--report-json", str(report_path),
    ])
    captured = capsys.readouterr()
    assert rc == EXIT_DEGRADED
    assert "topic 'ghost' vanished" in captured.err
    # The emitted plan covers exactly the surviving topics.
    from kafka_assigner_tpu.io.json_io import parse_reassignment_json

    payload = captured.out.split("NEW ASSIGNMENT:\n", 1)[1].strip()
    assert set(parse_reassignment_json(payload)) == {"events", "logs"}
    report = json.loads(report_path.read_text())
    assert report["status"] == "degraded"
    assert report["metrics"]["gauges"]["ingest.topics_skipped"] == 1


def test_mode3_output_unchanged_with_injection_disabled(snapshot, capsys):
    # The acceptance pin: with no faults scheduled, strict and best-effort
    # emit byte-identical stdout and both exit 0.
    assert run([
        "--zk_string", snapshot, "--mode", "PRINT_REASSIGNMENT",
    ]) == 0
    baseline = capsys.readouterr().out
    assert run([
        "--zk_string", snapshot, "--mode", "PRINT_REASSIGNMENT",
        "--failure-policy", "best-effort",
    ]) == 0
    assert capsys.readouterr().out == baseline


def test_cli_live_wire_nonode_race_best_effort(zk_server, monkeypatch, capsys):
    # End-to-end over a real socket: reply index 6 is the first topic
    # getData (children, 4 brokers, children, topics...), so the injected
    # NoNode simulates 'events' deleted between listing and read.
    monkeypatch.setenv("KA_ZK_CLIENT", "wire")
    monkeypatch.setenv("KA_FAULTS_SPEC", "reply:6=nonode")
    rc = run([
        "--zk_string", f"127.0.0.1:{zk_server.port}",
        "--mode", "PRINT_REASSIGNMENT", "--failure-policy", "best-effort",
    ])
    captured = capsys.readouterr()
    assert rc == EXIT_DEGRADED
    assert "vanished during the metadata scan" in captured.err
    payload = captured.out.split("NEW ASSIGNMENT:\n", 1)[1].strip()
    from kafka_assigner_tpu.io.json_io import parse_reassignment_json

    assert set(parse_reassignment_json(payload)) == {"logs"}


# --- write-seam scopes + backend-agnostic injection (ISSUE 7) ----------------

def test_parse_spec_write_seam_scopes():
    events = parse_spec(
        "write:0=drop;write:2=lost;converge:1=stall;wave:0=crash"
    )
    assert [(e.scope, e.index, e.kind) for e in events] == [
        ("write", 0, "drop"), ("write", 2, "lost"),
        ("converge", 1, "stall"), ("wave", 0, "crash"),
    ]
    with pytest.raises(FaultSpecError):
        parse_spec("write:0=stall")  # stall is a converge kind
    with pytest.raises(FaultSpecError):
        parse_spec("wave:0=drop")


def test_random_schedule_order_is_frozen():
    # New scopes APPEND to the draw order: a historical seed keeps drawing
    # the exact same events for the scopes it already covered (the legacy
    # five came first, in their old sorted order).
    from kafka_assigner_tpu.faults.inject import (
        FAULT_SCOPES,
        RANDOM_ORDER,
    )

    assert RANDOM_ORDER[:5] == (
        "connect", "handshake", "reply", "solve", "warmup"
    )
    assert set(RANDOM_ORDER) == set(FAULT_SCOPES)


def test_backend_reply_maps_kinds_to_adapter_failures():
    inj = FaultInjector(parse_spec(
        "reply:0=drop;reply:1=nonode;reply:2=nonode;reply:3=slow:0.001"
    ))
    with pytest.raises(ConnectionResetError):
        inj.backend_reply()
    with pytest.raises(NoNodeError):
        inj.backend_reply()
    with pytest.raises(KeyError):
        inj.backend_reply(missing_exc=KeyError)
    inj.backend_reply()  # slow: just delays
    inj.backend_reply()  # beyond the schedule: no-op
    assert [e.kind for e in inj.fired] == ["drop", "nonode", "nonode", "slow"]


def test_wave_fault_point_raises_exec_crash():
    from kafka_assigner_tpu.faults.inject import (
        InjectedExecCrash,
        fault_point,
    )

    faults.install(FaultInjector(parse_spec("wave:1=crash")))
    fault_point("wave")            # index 0: clean
    with pytest.raises(InjectedExecCrash):
        fault_point("wave")        # index 1: the kill
    fault_point("wave")            # schedule exhausted


def test_write_and_converge_hooks():
    inj = FaultInjector(parse_spec(
        "write:0=drop;write:1=lost;converge:0=stall"
    ))
    with pytest.raises(ConnectionResetError):
        inj.write_attempt()
    assert inj.write_attempt() == "lost"
    assert inj.write_attempt() is None
    assert inj.converge_poll() is True
    assert inj.converge_poll() is False


def test_fake_kazoo_reply_drop_is_an_ingest_failure(monkeypatch):
    from kafka_assigner_tpu.io.zk import ZkBackend

    from .test_backends import _install_fake_kazoo

    znodes = {
        "/brokers/ids": {"1": json.dumps({"host": "h1", "port": 9092})},
        "/brokers/topics": {
            "events": json.dumps({"partitions": {"0": [1]}}),
        },
    }
    _install_fake_kazoo(monkeypatch, znodes)
    faults.install(FaultInjector(parse_spec("reply:0=drop")))
    backend = ZkBackend("zkhost:2181")
    with pytest.raises(ConnectionResetError, match="injected fault"):
        backend.brokers()
    backend.close()


def test_fake_kazoo_nonode_best_effort_skips_topic(monkeypatch):
    from kafka_assigner_tpu.io.zk import ZkBackend

    from .test_backends import _install_fake_kazoo

    znodes = {
        "/brokers/ids": {"1": json.dumps({"host": "h1", "port": 9092})},
        "/brokers/topics": {
            "events": json.dumps({"partitions": {"0": [1]}}),
            "logs": json.dumps({"partitions": {"0": [1]}}),
        },
    }
    _install_fake_kazoo(monkeypatch, znodes)
    faults.install(FaultInjector(parse_spec("reply:0=nonode")))
    backend = ZkBackend("zkhost:2181")
    got = list(backend.fetch_topics(["events", "logs"], missing="skip"))
    assert got[0] == ("events", None)      # the injected vanish
    assert got[1] == ("logs", {0: [1]})    # the stream keeps flowing
    backend.close()


def test_fake_kazoo_connect_blackhole(monkeypatch):
    from kafka_assigner_tpu.io.zk import ZkBackend

    from .test_backends import _install_fake_kazoo

    _install_fake_kazoo(monkeypatch, {"/brokers/ids": {}})
    faults.install(FaultInjector(parse_spec("connect:0=blackhole")))
    with pytest.raises(ConnectionRefusedError, match="injected fault"):
        ZkBackend("zkhost:2181")


def test_fake_admin_reply_drop_and_connect_blackhole(monkeypatch):
    from kafka_assigner_tpu.io.kafka_admin import KafkaAdminBackend

    from .test_backends import _install_fake_confluent

    _install_fake_confluent(monkeypatch)
    faults.install(FaultInjector(parse_spec("reply:0=drop")))
    backend = KafkaAdminBackend("b1:9092")
    with pytest.raises(ConnectionResetError, match="injected fault"):
        backend.brokers()
    faults.install(FaultInjector(parse_spec("connect:0=blackhole")))
    with pytest.raises(ConnectionRefusedError, match="injected fault"):
        KafkaAdminBackend("b1:9092")


def test_fake_admin_nonode_vanishes_topic_in_skip_lane(monkeypatch, capsys):
    from kafka_assigner_tpu.io.kafka_admin import KafkaAdminBackend

    from .test_backends import _install_fake_confluent

    _install_fake_confluent(monkeypatch)
    # Index 1: the brokers() probe consumes 0, the batched skip-lane read
    # consumes 1 — its KeyError sends every topic through per-topic probes,
    # which resolve, so only the stream CONTRACT is degraded, not the data.
    faults.install(FaultInjector(parse_spec("reply:1=nonode")))
    backend = KafkaAdminBackend("b1:9092")
    backend.brokers()
    got = dict(backend.fetch_topics(["events", "logs"], missing="skip"))
    assert got["events"] == {0: [1, 2], 1: [2, 1]}
    assert got["logs"] == {0: [2]}


def test_fake_admin_exec_surface_with_kip455(monkeypatch):
    import sys
    import types

    from kafka_assigner_tpu.io.kafka_admin import KafkaAdminBackend

    calls = []

    class KafkaAdminClient:
        def __init__(self, bootstrap_servers):
            pass

        def describe_topics(self, topics):
            data = {"events": [
                {"partition": 0, "replicas": [1, 2], "isr": [1]},
            ]}
            return [{"topic": t, "partitions": data[t]} for t in topics
                    if t in data]

        def alter_partition_reassignments(self, reassignments):
            calls.append(reassignments)

        def close(self):
            pass

    pkg = types.ModuleType("kafka")
    pkg.KafkaAdminClient = KafkaAdminClient
    monkeypatch.setitem(sys.modules, "kafka", pkg)

    # Injectors resolve at backend construction (one coherent schedule per
    # client): the SECOND write is the acked-but-lost one.
    faults.install(FaultInjector(parse_spec("write:1=lost")))
    backend = KafkaAdminBackend("b1:9092")
    assert backend.supports_execution() is True
    backend.apply_assignment({"events": {0: [2, 1]}})
    assert calls == [{("events", 0): [2, 1]}]
    state = backend.read_assignment_state(["events"])
    assert state["events"][0].replicas == [1, 2]
    assert state["events"][0].isr == [1]  # real ISR, not the fallback
    # The write seam fires here like on any backend: an acked-but-lost
    # write never reaches the client call.
    backend.apply_assignment({"events": {0: [9, 1]}})
    assert len(calls) == 1


def test_fake_admin_without_kip455_refuses_execution(monkeypatch):
    from kafka_assigner_tpu.errors import ExecuteError
    from kafka_assigner_tpu.io.kafka_admin import KafkaAdminBackend

    from .test_backends import _install_fake_confluent

    _install_fake_confluent(monkeypatch)
    backend = KafkaAdminBackend("b1:9092")
    assert backend.supports_execution() is False
    with pytest.raises(ExecuteError, match="cannot execute"):
        backend.apply_assignment({"events": {0: [1]}})


# --- @cluster-addressed events (ISSUE 9) -------------------------------------

def test_parse_spec_cluster_addressing():
    events = parse_spec(
        "session@west:1=expire;resync@east-2:0=stall;watch@a.b:2=drop"
    )
    assert FaultEvent("session", 1, "expire", None, "west") in events
    assert FaultEvent("resync", 0, "stall", None, "east-2") in events
    assert FaultEvent("watch", 2, "drop", None, "a.b") in events
    # str round-trips through the parser
    for ev in events:
        assert parse_spec(str(ev)) == [ev]


@pytest.mark.parametrize("bad", [
    "session@:0=expire",       # empty cluster name
    "session@we st:0=expire",  # whitespace in cluster name
    "session@w/e:0=expire",    # illegal character
])
def test_parse_spec_rejects_bad_cluster(bad):
    with pytest.raises(FaultSpecError):
        parse_spec(bad)


def test_cluster_events_fire_at_per_cluster_indexes():
    """A @cluster event fires at that cluster's OWN per-scope index —
    other clusters' hook consults never consume it, however the daemon
    interleaves its supervisors."""
    inj = FaultInjector(parse_spec("session@west:1=expire"))
    # east consults twice first: west's counter is untouched
    assert not inj.session_check(cluster="east")
    assert not inj.session_check(cluster="east")
    assert not inj.session_check(cluster="west")   # west index 0
    assert inj.session_check(cluster="west")       # west index 1 -> fires
    assert not inj.session_check(cluster="west")   # one-shot


def test_clusterless_events_keep_the_global_counter():
    """Back-compat: a clusterless event fires at the GLOBAL per-scope
    index regardless of which cluster consults — byte-identical to every
    historical schedule."""
    inj = FaultInjector(parse_spec("session:1=expire"))
    assert not inj.session_check(cluster="a")  # global index 0
    assert inj.session_check(cluster="b")      # global index 1 -> fires
    inj2 = FaultInjector(parse_spec("watch:0=drop"))
    assert inj2.watch_delivery()               # clusterless consult works too


def test_cluster_scoped_resync_stall_raises_only_for_its_cluster():
    inj = FaultInjector(parse_spec("resync@a:0=stall"))
    inj.resync_attempt(cluster="b")  # b's index 0: no event
    with pytest.raises(InjectedResyncStall):
        inj.resync_attempt(cluster="a")


def test_global_event_does_not_swallow_cluster_event():
    """A clusterless event claiming a consult must not CONSUME the
    cluster's own index: the @cluster event fires at that cluster's next
    consult instead of vanishing silently."""
    inj = FaultInjector(parse_spec("session:0=expire;session@west:0=expire"))
    assert inj.session_check(cluster="west")  # the global event fires
    assert inj.session_check(cluster="west")  # west's own event, not lost
    assert len(inj.fired) == 2


# --- the controller seams (ISSUE 15) -----------------------------------------

def test_controller_spec_parses_and_validates():
    events = parse_spec(
        "controller:0=verdict-flap;controller:1=exec-crash;"
        "controller@west:0=regress"
    )
    assert [(e.scope, e.index, e.kind, e.cluster) for e in events] == [
        ("controller", 0, "verdict-flap", None),
        ("controller", 1, "exec-crash", None),
        ("controller", 0, "regress", "west"),
    ]
    with pytest.raises(FaultSpecError):
        parse_spec("controller:0=drop")  # not a controller kind
    with pytest.raises(FaultSpecError):
        parse_spec("reply:0=verdict-flap")  # controller-only kind


def test_controller_point_keeps_per_kind_counters():
    # controller:1=exec-crash means "the SECOND wave boundary", however
    # many evaluations (verdict-flap consults) ran before it — each seam
    # counts its own consults.
    inj = FaultInjector(parse_spec("controller:1=exec-crash"))
    from kafka_assigner_tpu.faults.inject import InjectedExecCrash

    assert inj.controller_point("verdict-flap") is False  # eval 0
    assert inj.controller_point("verdict-flap") is False  # eval 1
    assert inj.controller_point("exec-crash") is False    # wave 0
    with pytest.raises(InjectedExecCrash):
        inj.controller_point("exec-crash")                # wave 1: fires
    assert [str(e) for e in inj.fired] == ["controller:1=exec-crash"]


def test_controller_point_kind_mismatch_never_fires():
    # A scheduled regress event is invisible to the exec-crash seam even
    # at the matching index: kinds bind to their seams.
    inj = FaultInjector(parse_spec("controller:0=regress"))
    assert inj.controller_point("exec-crash") is False
    assert inj.controller_point("verdict-flap") is False
    assert inj.controller_point("regress") is True
    assert [e.kind for e in inj.fired] == ["regress"]


def test_controller_point_cluster_addressing():
    inj = FaultInjector(parse_spec("controller@a:0=verdict-flap"))
    # Another cluster's consults never fire it and never consume a's index.
    assert inj.controller_point("verdict-flap", cluster="b") is False
    assert inj.controller_point("verdict-flap", cluster="a") is True
    assert inj.controller_point("verdict-flap", cluster="a") is False
    assert [str(e) for e in inj.fired] == ["controller@a:0=verdict-flap"]
