"""Wave-machinery boundary pins (VERDICT r4 items 4 & 6).

The giant-shape gate (``ops/assignment.py:DENSE_MASK_BUDGET``) flips three
correctness-relevant behaviors at once: dense-leg demotion, slot-packed fast
waves, and the quota-balance insertion before every node-per-wave balance
leg. These were guarded only by reasoning in comments; here the flip is
exercised on small instances via ``KA_DENSE_MASK_BUDGET`` (the
``KA_WHATIF_MEMBUDGET`` treatment), and the exactly-saturated instance —
the class the reference's own first-fit provably dead-ends on
(``KafkaAssignmentStrategy.java:29-30``) — is pinned as solved with optimal
movement on BOTH sides of the flip.

The env knob is read at trace time, so every flip is bracketed by
``jax.clear_caches()`` (and the fixture restores + clears afterwards so no
later test can reuse a flipped-budget executable).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assigner_tpu.assigner import TopicAssigner
from kafka_assigner_tpu.models.synthetic import rack_striped_cluster
from kafka_assigner_tpu.ops import assignment as A
from kafka_assigner_tpu.solvers.tpu import TpuSolver

from .helpers import moved_replicas


def _moved(topics, pairs):
    cur = dict(topics)
    return sum(moved_replicas(cur[t], a) for t, a in pairs)


@pytest.fixture
def budget_flip(monkeypatch):
    """Set KA_DENSE_MASK_BUDGET for the test and guarantee no flipped-budget
    compiled program leaks into later tests."""

    def set_budget(value: int):
        monkeypatch.setenv("KA_DENSE_MASK_BUDGET", str(value))
        jax.clear_caches()

    yield set_budget
    monkeypatch.delenv("KA_DENSE_MASK_BUDGET", raising=False)
    jax.clear_caches()


def _saturated_instance():
    """Scaled-down mirror of the giant replace-100 showcase: 50 brokers /
    5 racks, one 1000-partition RF-3 topic (60 replicas/broker), replace
    brokers 0..9 with 50..59 — cap stays 60, so orphans (600) == free slots
    (600): exactly saturated."""
    topic_map, _, racks = rack_striped_cluster(
        50, 1, 1000, 3, 5, name_fmt="sat-{:02d}", extra_brokers=10
    )
    topics = list(topic_map.items())
    live = set(range(10, 60))
    rack_map = {b: racks[b] for b in live}
    return topics, live, rack_map


def test_saturated_solved_on_both_sides_of_budget_flip(budget_flip):
    """The exactly-saturated instance solves with optimal movement (exactly
    the replaced brokers' replicas) through the normal-shape chain AND
    through the giant-shape chain (slot-packed fast + quota balance),
    and the two agree on movement count."""
    topics, live, rack_map = _saturated_instance()
    base = TopicAssigner(TpuSolver()).generate_assignments(
        topics, live, rack_map, -1
    )
    m_base = _moved(topics, base)
    assert m_base == 600  # optimal: only the replaced brokers' replicas move

    budget_flip(50_000)  # < p_pad * n_pad = 1000 * 56: giant chain engages
    flipped = TopicAssigner(TpuSolver()).generate_assignments(
        topics, live, rack_map, -1
    )
    assert _moved(topics, flipped) == m_base


def test_expansion_movement_parity_across_budget_flip(budget_flip):
    """Non-saturated instance (the giant expansion's shape: added brokers
    striped one per rack; cap drops 120 -> 110, every original broker sheds
    10, slack 50): the slot-packed fast leg (flipped budget) moves exactly
    what the node-per-wave fast leg (default) moves."""
    topic_map, _, racks = rack_striped_cluster(
        50, 1, 2000, 3, 5, name_fmt="exp-{:02d}", extra_brokers=5
    )
    topics = list(topic_map.items())
    live = set(range(55))  # expansion: +5 brokers (one per rack)
    rack_map = {b: racks[b] for b in live}
    base = TopicAssigner(TpuSolver()).generate_assignments(
        topics, live, rack_map, -1
    )
    m_base = _moved(topics, base)
    assert m_base == 500  # optimal: 10 shed replicas per original broker

    budget_flip(100_000)  # < p_pad * n_pad = 2000 * 64
    flipped = TopicAssigner(TpuSolver()).generate_assignments(
        topics, live, rack_map, -1
    )
    assert _moved(topics, flipped) == m_base


def test_saturated_part_sharded_equals_unsharded_on_quota_chain(budget_flip):
    """The 8-way partition-sharded solve through the GIANT chain (slot-
    packed fast + balance_quota hybrid) is bit-identical to the unsharded
    one on the saturated instance — the round-4 sharded-saturated proof
    predates the quota leg, so the new wave bodies' cumsum/rank ops under
    GSPMD need their own equality pin."""
    from kafka_assigner_tpu.parallel.mesh import build_mesh

    topics, live, rack_map = _saturated_instance()
    budget_flip(50_000)
    unsharded = TopicAssigner(TpuSolver()).generate_assignments(
        topics, live, rack_map, -1
    )
    mesh = build_mesh(1, 8)  # all 8 devices on the partition axis
    sharded = TopicAssigner(TpuSolver(mesh=mesh)).generate_assignments(
        topics, live, rack_map, -1
    )
    assert sharded == unsharded
    assert _moved(topics, sharded) == 600


def test_quota_leg_solves_saturated_alone(budget_flip, monkeypatch):
    """The balance_quota hybrid (proportional drain + node-per-wave endgame)
    completes the saturated instance BY ITSELF — no rescue legs behind it —
    with optimal movement. This is the wave-count fix for the ~107-133 s
    strand-then-rescue path on the giant showcase (VERDICT r4 item 4)."""
    topics, live, rack_map = _saturated_instance()
    monkeypatch.setenv("KA_WAVE_MODE", "balance_quota")
    jax.clear_caches()
    out = TopicAssigner(TpuSolver()).generate_assignments(
        topics, live, rack_map, -1
    )
    assert _moved(topics, out) == 600
    monkeypatch.delenv("KA_WAVE_MODE")
    jax.clear_caches()


def test_huge_npad_wave_plan_degradation():
    """The int32 key-packing bound (n_pad^2 >= BIG): multi-leg chains degrade
    to (dense, seq); the balance-family modes fail loudly instead of
    silently changing algorithm."""
    big_n = 32768  # 32768^2 > 0x3FFFFFFF
    legs, _ = A._resolve_wave_plan("auto", big_n, 16)
    assert legs == ("dense", "seq")
    legs, _ = A._resolve_wave_plan("fast", big_n, 16)
    assert legs == ("dense",)
    # seq does no key packing and must NOT degrade — the RF-decrease compat
    # mode's three-backend byte parity rides on it at every scale.
    legs, _ = A._resolve_wave_plan("seq", big_n, 16)
    assert legs == ("seq",)
    for mode in ("balance", "balance_quota"):
        with pytest.raises(ValueError, match="int32"):
            A._resolve_wave_plan(mode, big_n, 16)
    # The hoisted-segments helper resolves through the same plan: no segment
    # arrays are built for the degraded chain.
    rack_idx = jnp.zeros((big_n,), dtype=jnp.int32)
    assert (
        A._hoisted_segments(
            rack_idx, 16, A.default_alive(rack_idx, 16), "auto", 16
        )
        is None
    )


def test_huge_npad_dense_fallback_executes():
    """The degraded (dense, seq) chain actually RUNS at an overflowing n_pad:
    a hand-built 8-partition RF-2 problem on 16 real nodes padded to 32768
    places every replica through the dense wave."""
    big_n = 32768
    n, p, rf = 16, 8, 2
    rack_idx = np.full((big_n,), 9, dtype=np.int32)
    rack_idx[:n] = np.arange(n, dtype=np.int32) % 4  # 4 racks
    rack_idx = jnp.asarray(rack_idx)
    alive = A.default_alive(rack_idx, n)
    cap = jnp.int32((p * rf + n - 1) // n + 1)
    state = A.AssignState(
        acc_nodes=jnp.full((p, rf), -1, dtype=jnp.int32),
        acc_count=jnp.zeros((p,), dtype=jnp.int32),
        node_load=jnp.zeros((big_n + 1,), dtype=jnp.int32),
        deficit=jnp.full((p,), rf, dtype=jnp.int32),
        infeasible=jnp.asarray(False),
    )
    pos = jnp.where(
        alive, (jnp.arange(big_n, dtype=jnp.int32) + 3) % n, A.BIG
    )
    out = A.spread_orphans(state, rack_idx, pos, cap, n, wave_mode="auto")
    assert not bool(out.infeasible)
    assert int(jnp.sum(out.deficit)) == 0
    nodes = np.asarray(out.acc_nodes)
    assert nodes.min() >= 0 and nodes.max() < n
    # rack exclusivity holds per partition
    racks = np.asarray(rack_idx)[nodes]
    assert all(len(set(r)) == rf for r in racks)