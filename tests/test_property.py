"""Property-based tests (hypothesis): generated clusters across the whole
input space, asserting the cross-solver contracts that must hold wherever a
solve succeeds — validity invariants, greedy/native byte-equality, and
greedy/tpu movement parity."""
from __future__ import annotations

import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from kafka_assigner_tpu.assigner import TopicAssigner

from .helpers import moved_replicas, native_available, verify_full_invariants


@st.composite
def clusters(draw):
    """A random cluster + rack-valid current assignment + membership change."""
    n_racks = draw(st.integers(2, 6))
    per_rack = draw(st.integers(1, 4))
    n_brokers = n_racks * per_rack
    rf = draw(st.integers(1, min(3, n_racks)))
    n_parts = draw(st.integers(1, 24))
    base = list(range(100, 100 + n_brokers))
    racks = {b: f"r{i % n_racks}" for i, b in enumerate(base)}
    # rack-interleaved striping => rack-valid, balanced start
    by_rack: dict = {}
    for b in base:
        by_rack.setdefault(racks[b], []).append(b)
    inter = [
        by_rack[r][d]
        for d in range(per_rack)
        for r in sorted(by_rack)
    ]
    offset = draw(st.integers(0, n_brokers - 1))
    current = {
        p: [inter[(offset + p + i) % n_brokers] for i in range(rf)]
        for p in range(n_parts)
    }
    # membership change: remove up to 1 broker per rack, add up to 3
    n_remove = draw(st.integers(0, min(n_racks, n_brokers - rf)))
    removed = {by_rack[f"r{i}"][0] for i in range(n_remove)}
    n_add = draw(st.integers(0, 3))
    live = [b for b in base if b not in removed]
    for j in range(n_add):
        nb = 100 + n_brokers + j
        live.append(nb)
        racks[nb] = f"r{j % n_racks}"
    rack_map = {b: racks[b] for b in live}
    topic = draw(st.sampled_from(["t", "events", "__consumer_offsets", "x-1"]))
    return topic, current, set(live), rack_map, rf


@pytest.mark.skipif(not native_available(), reason="no C++ toolchain")
@settings(max_examples=35, deadline=None)
@given(clusters())
def test_greedy_native_byte_equality(case):
    topic, current, live, rack_map, rf = case
    try:
        g = TopicAssigner("greedy").generate_assignment(topic, current, live, rack_map, -1)
    except ValueError as e:
        try:
            TopicAssigner("native").generate_assignment(topic, current, live, rack_map, -1)
            raise AssertionError("native succeeded where greedy failed") from e
        except ValueError:
            return
    n = TopicAssigner("native").generate_assignment(topic, current, live, rack_map, -1)
    assert g == n


@settings(max_examples=30, deadline=None)
@given(clusters())
def test_tpu_invariants_and_movement(case):
    topic, current, live, rack_map, rf = case
    try:
        g = TopicAssigner("greedy").generate_assignment(topic, current, live, rack_map, -1)
        greedy_ok = True
    except ValueError:
        greedy_ok = False
    try:
        t = TopicAssigner("tpu").generate_assignment(topic, current, live, rack_map, -1)
    except ValueError:
        # tpu may fail ONLY where greedy also fails (it is a strict superset)
        assert not greedy_ok
        return
    verify_full_invariants(t, rack_map, sorted(live), rf)
    if greedy_ok:
        assert moved_replicas(current, t) == moved_replicas(current, g)


@settings(max_examples=20, deadline=None)
@given(clusters())
def test_determinism(case):
    topic, current, live, rack_map, rf = case
    try:
        a = TopicAssigner("greedy").generate_assignment(topic, current, live, rack_map, -1)
    except ValueError:
        return
    b = TopicAssigner("greedy").generate_assignment(topic, current, live, rack_map, -1)
    assert a == b
