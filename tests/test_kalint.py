"""kalint rule fixtures (each rule tripped and cleared on small snippets), a
repo-wide clean run, and the loud-fallback contract of the typed knob
accessors (``utils/env.py`` house rule: mis-set knobs must never silently
change the measured configuration)."""
from __future__ import annotations

import pytest

from kafka_assigner_tpu.analysis import kalint
from kafka_assigner_tpu.utils.env import (
    KNOBS,
    env_bool,
    env_choice,
    env_float,
    env_int,
    env_str,
    knob_default,
)


def rules_of(findings):
    return {f.rule for f in findings}


# --- KA001: raw os.environ access to KA_* outside the registry --------------

KA001_SNIPPET = 'import os\nmode = os.environ.get("KA_WAVE_MODE", "auto")\n'


def test_ka001_trips_on_raw_environ_get():
    findings = kalint.lint_source(KA001_SNIPPET, "solvers/foo.py")
    assert any(f.rule == "KA001" and f.line == 2 for f in findings)


@pytest.mark.parametrize("line", [
    'v = os.environ["KA_LEADER_CHUNK"]',
    'v = os.getenv("KA_LEADER_CHUNK")',
    'v = "KA_LEADER_CHUNK" in os.environ',
    'os.environ["KA_LEADER_CHUNK"] = "4"',
])
def test_ka001_trips_on_every_access_form(line):
    findings = kalint.lint_source(f"import os\n{line}\n", "foo.py")
    assert "KA001" in rules_of(findings)


@pytest.mark.parametrize("src", [
    'from os import environ\nv = environ.get("KA_LEADER_CHUNK")\n',
    'from os import environ as env\nv = env["KA_LEADER_CHUNK"]\n',
    'from os import getenv\nv = getenv("KA_LEADER_CHUNK")\n',
    'from os import getenv as ge\nv = ge("KA_LEADER_CHUNK")\n',
    'import os as o\nv = o.environ.get("KA_LEADER_CHUNK")\n',
    'import os as o\nv = o.getenv("KA_LEADER_CHUNK")\n',
])
def test_ka001_trips_on_import_aliases(src):
    assert "KA001" in rules_of(kalint.lint_source(src, "foo.py"))


def test_ka001_exempts_the_registry_module():
    findings = kalint.lint_source(KA001_SNIPPET, "utils/env.py")
    assert "KA001" not in rules_of(findings)


def test_ka001_ignores_non_knob_environ_access():
    src = 'import os\nflags = os.environ.get("XLA_FLAGS", "")\n'
    assert kalint.lint_source(src, "foo.py") == []


# --- KA002: host sync / nondeterminism in traced kernel code -----------------

def test_ka002_trips_module_wide_in_kernel_modules():
    src = "import time\n\ndef helper():\n    return time.time()\n"
    findings = kalint.lint_source(src, "ops/assignment.py")
    assert any(f.rule == "KA002" and f.line == 4 for f in findings)
    # The same code outside kernel modules and outside any jit root is host
    # driver code — allowed.
    assert kalint.lint_source(src, "generator.py") == []


def test_ka002_trips_inside_jit_rooted_functions():
    src = (
        "import jax\n"
        "import numpy as np\n"
        "\n"
        "def kernel(x):\n"
        "    return np.asarray(x)\n"
        "\n"
        "kernel_jit = jax.jit(kernel, static_argnames=())\n"
    )
    findings = kalint.lint_source(src, "solvers/custom.py")
    assert any(f.rule == "KA002" and f.line == 5 for f in findings)


def test_ka002_follows_same_module_callees_of_jit_roots():
    src = (
        "import jax\n"
        "import random\n"
        "\n"
        "def helper():\n"
        "    return random.random()\n"
        "\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    return x + helper()\n"
    )
    findings = kalint.lint_source(src, "solvers/custom.py")
    assert any(f.rule == "KA002" and f.line == 5 for f in findings)


def test_ka002_banned_calls_catalogue():
    src = (
        "import jax, time, random\n"
        "import numpy as np\n"
        "\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    a = jax.device_get(x)\n"
        "    b = x.item()\n"
        "    c = np.random.rand(3)\n"
        "    d = time.perf_counter()\n"
        "    return a, b, c, d\n"
    )
    findings = [f for f in kalint.lint_source(src, "foo.py") if f.rule == "KA002"]
    assert {f.line for f in findings} == {6, 7, 8, 9}


# --- KA003: unregistered KA_* literals ---------------------------------------

def test_ka003_trips_on_typo_knob():
    findings = kalint.lint_source('NAME = "KA_TYPO_NOT_A_KNOB"\n', "foo.py")
    assert "KA003" in rules_of(findings)


def test_ka003_accepts_registered_knob_literals():
    assert kalint.lint_source('NAME = "KA_WAVE_MODE"\n', "foo.py") == []


# --- KA004: README knob-table drift ------------------------------------------

def test_ka004_flags_missing_knob():
    findings = kalint.check_readme("table mentions only KA_WAVE_MODE here",
                                   knobs=["KA_WAVE_MODE", "KA_LEADER_CHUNK"])
    assert [f.rule for f in findings] == ["KA004"]
    assert "KA_LEADER_CHUNK" in findings[0].message


def test_ka004_clean_when_all_knobs_present():
    text = " ".join(KNOBS)
    assert kalint.check_readme(text) == []


def test_ka004_prefix_of_another_knob_is_not_a_match():
    findings = kalint.check_readme(
        "only `KA_COMPILE_CACHE_DIR` is documented",
        knobs=["KA_COMPILE_CACHE", "KA_COMPILE_CACHE_DIR"],
    )
    assert [f.rule for f in findings] == ["KA004"]
    assert "KA_COMPILE_CACHE " in findings[0].message + " "


# --- KA005: plan JSON emission outside io/json_io.py -------------------------

KA005_SNIPPET = "import json\n\ndef emit(d):\n    return json.dumps(d)\n"


def test_ka005_trips_outside_the_boundary():
    findings = kalint.lint_source(KA005_SNIPPET, "generator.py")
    assert any(f.rule == "KA005" and f.line == 4 for f in findings)


def test_ka005_exempts_json_io():
    assert kalint.lint_source(KA005_SNIPPET, "io/json_io.py") == []


# --- KA006: jnp. calls at module import time ---------------------------------

def test_ka006_trips_on_import_time_jnp_call():
    src = "import jax.numpy as jnp\nZEROS = jnp.zeros((8,))\n"
    findings = kalint.lint_source(src, "foo.py")
    assert any(f.rule == "KA006" and f.line == 2 for f in findings)


def test_ka006_trips_on_spelled_out_chain_and_aliases():
    assert "KA006" in rules_of(
        kalint.lint_source("import jax\nX = jax.numpy.ones(3)\n", "foo.py")
    )
    assert "KA006" in rules_of(
        kalint.lint_source("from jax import numpy as xp\nX = xp.ones(3)\n",
                           "foo.py")
    )


def test_ka006_allows_calls_inside_functions():
    src = (
        "def f():\n"
        "    import jax.numpy as jnp\n"
        "    return jnp.zeros((8,))\n"
        "g = lambda jnp: jnp.zeros(1)\n"
    )
    assert kalint.lint_source(src, "foo.py") == []


def test_ka006_trips_on_default_args_and_class_bodies():
    # Decorators, default arguments, and class bodies all execute at import.
    src = (
        "import jax.numpy as jnp\n"
        "def f(x=jnp.zeros(1)):\n"
        "    return x\n"
        "class C:\n"
        "    attr = jnp.ones(2)\n"
    )
    findings = [
        f for f in kalint.lint_source(src, "foo.py") if f.rule == "KA006"
    ]
    assert {f.line for f in findings} == {2, 5}


def test_ka006_does_not_flag_other_jax_api_calls():
    src = "import jax\nkernel_jit = jax.jit(lambda x: x)\n"
    assert "KA006" not in rules_of(kalint.lint_source(src, "foo.py"))


# --- KA007: jit-traced functions closing over mutable globals ----------------

KA007_SNIPPET = (
    "import jax\n"
    "CACHE = {}\n"
    "\n"
    "@jax.jit\n"
    "def kernel(x):\n"
    "    return x + CACHE['bias']\n"
)


def test_ka007_trips_on_mutable_global_read_under_trace():
    findings = kalint.lint_source(KA007_SNIPPET, "solvers/custom.py")
    assert any(
        f.rule == "KA007" and f.line == 6 and "CACHE" in f.message
        for f in findings
    )


def test_ka007_untraced_function_is_host_code():
    src = "CACHE = {}\n\ndef host():\n    return CACHE\n"
    assert kalint.lint_source(src, "generator.py") == []


@pytest.mark.parametrize("binding", [
    "TABLE = [1, 2]",
    "TABLE = set()",
    "TABLE = dict(a=1)",
    "TABLE = {k: k for k in range(3)}",
    "TABLE: dict = {}",
])
def test_ka007_mutable_binding_forms(binding):
    src = (
        f"import jax\n{binding}\n\n"
        "@jax.jit\ndef kernel(x):\n    return TABLE and x\n"
    )
    assert "KA007" in rules_of(kalint.lint_source(src, "foo.py"))


@pytest.mark.parametrize("binding", [
    "TABLE = (1, 2)",
    "TABLE = frozenset({1})",
    "from types import MappingProxyType\nTABLE = MappingProxyType({'a': 1})",
])
def test_ka007_immutable_bindings_are_clean(binding):
    src = (
        f"import jax\n{binding}\n\n"
        "@jax.jit\ndef kernel(x):\n    return TABLE and x\n"
    )
    assert "KA007" not in rules_of(kalint.lint_source(src, "foo.py"))


def test_ka007_local_shadow_is_clean():
    src = (
        "import jax\nCACHE = {}\n\n"
        "@jax.jit\ndef kernel(x):\n"
        "    CACHE = {'bias': 1}\n"
        "    return x + CACHE['bias']\n"
    )
    assert "KA007" not in rules_of(kalint.lint_source(src, "foo.py"))


def test_ka007_follows_same_module_callees_of_jit_roots():
    src = (
        "import jax\nMODES = {'a': 1}\n\n"
        "def resolve(m):\n"
        "    return MODES[m]\n\n"
        "def kernel(x, m):\n"
        "    return x * resolve(m)\n\n"
        "kernel_jit = jax.jit(kernel, static_argnames=('m',))\n"
    )
    findings = kalint.lint_source(src, "foo.py")
    assert any(f.rule == "KA007" and f.line == 5 for f in findings)


def test_ka007_trips_on_global_rebinding_under_trace():
    src = (
        "import jax\nSTATE = 0\n\n"
        "@jax.jit\ndef kernel(x):\n"
        "    global STATE\n"
        "    STATE = x\n"
        "    return x\n"
    )
    findings = kalint.lint_source(src, "foo.py")
    assert any(
        f.rule == "KA007" and "rebinds" in f.message for f in findings
    )


def test_ka007_one_finding_per_name_per_function():
    src = (
        "import jax\nCACHE = {}\n\n"
        "@jax.jit\ndef kernel(x):\n"
        "    return CACHE['a'] + CACHE['b'] + x\n"
    )
    findings = [
        f for f in kalint.lint_source(src, "foo.py") if f.rule == "KA007"
    ]
    assert len(findings) == 1


# --- KA008: silently-swallowed exceptions ------------------------------------

def test_ka008_trips_on_bare_pass():
    src = (
        "def f():\n"
        "    try:\n"
        "        work()\n"
        "    except OSError:\n"
        "        pass\n"
    )
    findings = kalint.lint_source(src, "foo.py")
    assert any(f.rule == "KA008" and f.line == 5 for f in findings)


def test_ka008_trips_on_bare_continue():
    src = (
        "def f(items):\n"
        "    for x in items:\n"
        "        try:\n"
        "            work(x)\n"
        "        except ValueError:\n"
        "            continue\n"
    )
    assert "KA008" in rules_of(kalint.lint_source(src, "foo.py"))


@pytest.mark.parametrize("body", [
    "raise",
    "log.warning('boom')",
    "count += 1",
    "x = fallback()",
])
def test_ka008_any_real_handling_is_clean(body):
    src = (
        "def f():\n"
        "    count = 0\n"
        "    try:\n"
        "        work()\n"
        "    except OSError:\n"
        f"        {body}\n"
    )
    assert "KA008" not in rules_of(kalint.lint_source(src, "foo.py"))


def test_ka008_reasoned_suppression_silences():
    src = (
        "def f():\n"
        "    try:\n"
        "        work()\n"
        "    except OSError:  # kalint: disable=KA008 -- best-effort cleanup\n"
        "        pass\n"
    )
    assert kalint.lint_source(src, "foo.py") == []


# --- KA009: ops/ jit dispatch confined to bucket-boundary modules ------------

KA009_SNIPPET = (
    "from ..ops.assignment import solve_batched_jit\n"
    "\n"
    "def run(currents, rack_idx, counters, jhashes, p_reals):\n"
    "    return solve_batched_jit(\n"
    "        currents, rack_idx, counters, jhashes, p_reals, n=8, rf=3)\n"
)


def test_ka009_trips_outside_boundary_modules():
    findings = kalint.lint_source(KA009_SNIPPET, "generator.py")
    assert any(
        f.rule == "KA009" and "bucket-boundary" in f.message
        for f in findings
    )


def test_ka009_boundary_modules_are_allowed():
    for relpath in sorted(kalint.BUCKET_BOUNDARY_MODULES):
        assert "KA009" not in rules_of(
            kalint.lint_source(KA009_SNIPPET, relpath)
        )


def test_ka009_module_attribute_dispatch_also_trips():
    src = (
        "from ..ops import assignment\n"
        "\n"
        "def run(c, r, j, p):\n"
        "    return assignment.place_scan_jit(c, r, j, p, n=8, rf=3)\n"
    )
    assert "KA009" in rules_of(kalint.lint_source(src, "io/zk.py"))


def test_ka009_non_jit_ops_imports_are_clean():
    # Importing helpers (constants, host-side utilities) is not a dispatch.
    src = (
        "from ..ops.assignment import WAVE_MODES\n"
        "\n"
        "def modes():\n"
        "    return tuple(WAVE_MODES)\n"
    )
    assert "KA009" not in rules_of(kalint.lint_source(src, "generator.py"))


# --- KA010: write opcodes only in the serial write path ----------------------

def test_ka010_trips_outside_the_wire_module():
    src = (
        "from .zkwire import OP_CREATE\n"
        "\n"
        "def sneaky_write(client, path):\n"
        "    client._call(OP_CREATE, path)\n"
    )
    findings = kalint.lint_source(src, "io/zk.py")
    assert any(
        f.rule == "KA010" and "serial write path" in f.message
        for f in findings
    )


def test_ka010_trips_on_attribute_references():
    src = (
        "from ..io import zkwire\n"
        "\n"
        "def sneaky(client, path):\n"
        "    return client._call(zkwire.OP_SET_DATA, path)\n"
    )
    assert "KA010" in rules_of(kalint.lint_source(src, "generator.py"))


def test_ka010_trips_inside_zkwire_pipelined_helpers():
    # Even the wire module itself may only touch write opcodes from the
    # serial write methods — a write op fed to the windowed helpers is the
    # exact bug class the rule exists for.
    src = (
        "OP_DELETE = 2\n"
        "\n"
        "def _iter_window(self, paths):\n"
        "    return self._send(OP_DELETE, paths)\n"
    )
    assert "KA010" in rules_of(kalint.lint_source(src, "io/zkwire.py"))


def test_ka010_serial_write_methods_are_allowed():
    src = (
        "OP_CREATE = 1\n"   # the Store-context definition is exempt too
        "\n"
        "def create(self, path, value):\n"
        "    return self._write_call(OP_CREATE, path)\n"
    )
    assert "KA010" not in rules_of(kalint.lint_source(src, "io/zkwire.py"))


def test_ka010_repo_wire_module_is_clean():
    from pathlib import Path

    import kafka_assigner_tpu

    pkg = Path(kafka_assigner_tpu.__file__).resolve().parent
    src = (pkg / "io" / "zkwire.py").read_text(encoding="utf-8")
    assert "KA010" not in rules_of(kalint.lint_source(src, "io/zkwire.py"))


# --- suppressions ------------------------------------------------------------

def test_suppression_with_reason_silences_the_finding():
    src = (
        "import json\n"
        "\n"
        "def emit(d):\n"
        "    return json.dumps(d)  # kalint: disable=KA005 -- fixture payload\n"
    )
    assert kalint.lint_source(src, "generator.py") == []


def test_suppression_on_the_line_above_also_counts():
    src = (
        "import json\n"
        "\n"
        "def emit(d):\n"
        "    # kalint: disable=KA005 -- fixture payload\n"
        "    return json.dumps(d)\n"
    )
    assert kalint.lint_source(src, "generator.py") == []


def test_reasonless_suppression_is_a_finding_and_does_not_suppress():
    src = (
        "import json\n"
        "\n"
        "def emit(d):\n"
        "    return json.dumps(d)  # kalint: disable=KA005\n"
    )
    rules = rules_of(kalint.lint_source(src, "generator.py"))
    assert rules == {"KA000", "KA005"}


def test_suppression_only_covers_named_rules():
    src = (
        "import os\n"
        "v = os.environ.get('KA_WAVE_MODE')  # kalint: disable=KA005 -- wrong rule\n"
    )
    assert "KA001" in rules_of(kalint.lint_source(src, "foo.py"))


def test_suppression_syntax_inside_strings_is_inert():
    # Documenting the syntax in a docstring or literal must neither install
    # a suppression nor trip the reasonless-suppression meta rule.
    src = (
        '"""Docs: write # kalint: disable=KA005 to suppress."""\n'
        "import json\n"
        "\n"
        "def emit(d):\n"
        "    s = 'ex: # kalint: disable=KA005 -- quoted reason'\n"
        "    return json.dumps(d), s\n"
    )
    assert rules_of(kalint.lint_source(src, "generator.py")) == {"KA005"}


# --- the package itself is clean ---------------------------------------------

def test_package_is_kalint_clean():
    findings = kalint.lint_package()
    assert findings == [], "\n".join(str(f) for f in findings)


# --- typed accessor house rule: warn loudly, fall back ----------------------

def test_env_float_warns_and_defaults_on_garbage(monkeypatch, capsys):
    # The KA_DEVICE_WATCHDOG_S bugfix: a bare float() here used to crash the
    # CLI on garbage instead of warning-and-defaulting.
    monkeypatch.setenv("KA_DEVICE_WATCHDOG_S", "ten seconds")
    assert env_float("KA_DEVICE_WATCHDOG_S") == 0.0
    assert "ignoring non-numeric KA_DEVICE_WATCHDOG_S" in capsys.readouterr().err


def test_env_float_parses_and_clamps(monkeypatch):
    monkeypatch.setenv("KA_DEVICE_WATCHDOG_S", "12.5")
    assert env_float("KA_DEVICE_WATCHDOG_S") == 12.5
    monkeypatch.setenv("KA_DEVICE_WATCHDOG_S", "-3")
    assert env_float("KA_DEVICE_WATCHDOG_S") == 0.0  # floor


def test_env_int_warns_and_defaults_on_garbage(monkeypatch, capsys):
    monkeypatch.setenv("KA_PLACE_CHUNK", "many")
    assert env_int("KA_PLACE_CHUNK") == 256
    assert "ignoring non-integer KA_PLACE_CHUNK" in capsys.readouterr().err
    monkeypatch.setenv("KA_PLACE_CHUNK", "-5")
    assert env_int("KA_PLACE_CHUNK") == 1  # floor clamp


@pytest.mark.parametrize("raw,expected", [
    ("1", True), ("true", True), ("YES", True), ("on", True),
    ("0", False), ("false", False), ("No", False), ("off", False),
])
def test_env_bool_truthiness_convention(monkeypatch, raw, expected):
    monkeypatch.setenv("KA_RF_DECREASE_COMPAT", raw)
    assert env_bool("KA_RF_DECREASE_COMPAT") is expected


def test_env_bool_warns_and_defaults_on_garbage(monkeypatch, capsys):
    monkeypatch.setenv("KA_RF_DECREASE_COMPAT", "maybe")
    assert env_bool("KA_RF_DECREASE_COMPAT") is False
    assert "ignoring non-boolean KA_RF_DECREASE_COMPAT" in capsys.readouterr().err
    monkeypatch.delenv("KA_RF_DECREASE_COMPAT")
    assert env_bool("KA_RF_DECREASE_COMPAT") is False
    # A default-on bool keeps its default under garbage too (loudly).
    monkeypatch.setenv("KA_HOSTCODEC", "maybe")
    assert env_bool("KA_HOSTCODEC") is True
    assert "KA_HOSTCODEC" in capsys.readouterr().err


def test_env_choice_warns_and_defaults_on_unknown(monkeypatch, capsys):
    monkeypatch.setenv("KA_ZK_CLIENT", "thrift")
    assert env_choice("KA_ZK_CLIENT") == "auto"
    assert "ignoring unknown KA_ZK_CLIENT" in capsys.readouterr().err


def test_env_choice_folds_case(monkeypatch):
    monkeypatch.setenv("KA_LOG", "debug")
    assert env_choice("KA_LOG") == "DEBUG"


def test_env_choice_strips_whitespace(monkeypatch, capsys):
    # Same forgiveness as env_bool: shell-export padding is not a misconfig.
    monkeypatch.setenv("KA_LOG", " DEBUG ")
    assert env_choice("KA_LOG") == "DEBUG"
    assert capsys.readouterr().err == ""


def test_env_choice_without_a_choice_set_is_a_programming_error():
    # KA_WAVE_MODE's choice set lives at the call site (WAVE_MODES); reading
    # it without one must raise, never pass raw through unvalidated.
    with pytest.raises(KeyError, match="no declared choice set"):
        env_choice("KA_WAVE_MODE")


def test_env_str_returns_raw_or_default(monkeypatch):
    monkeypatch.delenv("KA_PROFILE", raising=False)
    assert env_str("KA_PROFILE") is None
    monkeypatch.setenv("KA_PROFILE", "/tmp/trace")
    assert env_str("KA_PROFILE") == "/tmp/trace"


def test_unregistered_knob_is_a_programming_error():
    with pytest.raises(KeyError, match="not a registered knob"):
        env_int("KA_NOT_A_REGISTERED_KNOB")
    with pytest.raises(KeyError, match="not a registered knob"):
        knob_default("KA_NOT_A_REGISTERED_KNOB")


def test_registry_defaults_match_kernel_constants():
    # The registry is the single declaration; the ops constants must be
    # derived from it, not drift beside it.
    from kafka_assigner_tpu.ops.assignment import (
        DENSE_MASK_BUDGET,
        QUOTA_ENDGAME_HEADROOM,
        QUOTA_WAVE_TARGET,
    )

    assert DENSE_MASK_BUDGET == knob_default("KA_DENSE_MASK_BUDGET")
    assert QUOTA_WAVE_TARGET == knob_default("KA_QUOTA_WAVE_TARGET")
    assert QUOTA_ENDGAME_HEADROOM == knob_default("KA_QUOTA_ENDGAME")


# --- KA011: blocking recv/poll loops must consult a deadline -----------------

def test_ka011_trips_on_undeadlined_recv_loop():
    src = (
        "def pump(sock):\n"
        "    while True:\n"
        "        data = sock.recv(4)\n"
    )
    findings = [
        f for f in kalint.lint_source(src, "io/foo.py")
        if f.rule == "KA011"
    ]
    assert len(findings) == 1
    assert "no deadline" in findings[0].message


def test_ka011_trips_on_poll_and_accept_and_sleep_loops():
    for call in ("conn.accept()", "selector.select()", "time.sleep(1)"):
        src = (
            "def pump(x, conn, selector, time):\n"
            "    while True:\n"
            f"        {call}\n"
        )
        assert "KA011" in rules_of(kalint.lint_source(src, "foo.py")), call


def test_ka011_satisfied_by_deadline_knob_consult():
    src = (
        "from .utils.env import env_float\n"
        "\n"
        "def pump(sock):\n"
        '    deadline = env_float("KA_EXEC_POLL_TIMEOUT")\n'
        "    while True:\n"
        "        data = sock.recv(4)\n"
    )
    assert "KA011" not in rules_of(kalint.lint_source(src, "foo.py"))


def test_ka011_satisfied_by_settimeout():
    src = (
        "def pump(sock):\n"
        "    sock.settimeout(5.0)\n"
        "    while True:\n"
        "        data = sock.recv(4)\n"
    )
    assert "KA011" not in rules_of(kalint.lint_source(src, "foo.py"))


def test_ka011_ignores_bounded_while_and_nonblocking_bodies():
    src = (
        "def pump(sock, n, q):\n"
        "    while n:\n"            # not a forever loop
        "        sock.recv(4)\n"
        "        n -= 1\n"
        "    while True:\n"         # forever, but nothing blocking
        "        q.put(1)\n"
        "        break\n"
    )
    assert "KA011" not in rules_of(kalint.lint_source(src, "foo.py"))


def test_ka011_reasoned_suppression_holds():
    src = (
        "def pump(sock):\n"
        "    # kalint: disable=KA011 -- bounded by the caller-owned socket timeout\n"
        "    while True:\n"
        "        data = sock.recv(4)\n"
    )
    assert "KA011" not in rules_of(kalint.lint_source(src, "foo.py"))


# --- KA012: cross-bulkhead access in daemon request handlers ------------------

KA012_SNIPPET = (
    "def do_plan(daemon, name, params):\n"
    "    sup = daemon.supervisors[name]\n"
    "    return sup.backend.brokers(), sup.state.topic_names()\n"
)


def test_ka012_trips_in_daemon_service_modules():
    findings = kalint.lint_source(KA012_SNIPPET, "daemon/service.py")
    ka012 = [f for f in findings if f.rule == "KA012"]
    assert len(ka012) == 2  # one per attribute read (.backend, .state)
    assert all("cross-bulkhead" in f.message for f in ka012)


def test_ka012_silent_in_bulkhead_and_foreign_modules():
    # the supervisor OWNS its backend/cache; state.py IS the cache
    assert "KA012" not in rules_of(
        kalint.lint_source(KA012_SNIPPET, "daemon/supervisor.py")
    )
    assert "KA012" not in rules_of(
        kalint.lint_source(KA012_SNIPPET, "daemon/state.py")
    )
    # modules outside daemon/ are out of scope
    assert "KA012" not in rules_of(
        kalint.lint_source(KA012_SNIPPET, "cli.py")
    )


def test_ka012_ignores_stores_and_method_calls():
    src = (
        "def setup(self):\n"
        "    self.state = object()\n"        # Store: building one's own
        "    view = self.sup.state_view()\n"  # method named state_view: fine
        "    self.sup.handle('/plan', {})\n"
    )
    assert "KA012" not in rules_of(
        kalint.lint_source(src, "daemon/service.py")
    )


def test_ka012_suppressible_with_reason():
    src = (
        "def peek(sup):\n"
        "    # kalint: disable=KA012 -- test-only introspection hook\n"
        "    return sup.state\n"
    )
    assert "KA012" not in rules_of(
        kalint.lint_source(src, "daemon/service.py")
    )


# --- KA013: metric/span names must come from the declared registry ------------

def test_ka013_trips_on_typod_metric_name():
    src = (
        "from ..obs.metrics import counter_add\n"
        "def f():\n"
        '    counter_add("daemon.requestz")\n'  # typo: would vanish silently
    )
    findings = kalint.lint_source(src, "daemon/foo.py")
    ka013 = [f for f in findings if f.rule == "KA013"]
    assert len(ka013) == 1 and "daemon.requestz" in ka013[0].message


@pytest.mark.parametrize("line", [
    'obs.counter_add("daemon.requests")',       # attribute-call form
    'gauge_set("plan.moves", 3)',
    'hist_observe("zk.op_ms", 1.0)',
    'with hist_ms("zk.pipeline.batch_ms"): pass',
    'with span("encode"): pass',
    'record_span("daemon/resync", 1.0)',        # _metric composes on this
    'self._count("daemon.breaker_opened")',
    'self._metric("daemon/request")',
    'with span("solve", hist="exec.wave_ms"): pass',
])
def test_ka013_declared_names_are_clean(line):
    src = f"def f(self):\n    {line}\n"
    assert "KA013" not in rules_of(kalint.lint_source(src, "foo.py"))


@pytest.mark.parametrize("line,needle", [
    ('span("enc0de")', "enc0de"),                       # span typo
    ('record_span("daemon/resink", 1.0)', "daemon/resink"),
    ('self._count("daemon.breaker_openend")', "breaker_openend"),
    ('self._metric("daemon/requets")', "daemon/requets"),
    ('hist_ms("zk.op_mss")', "zk.op_mss"),
    ('span("solve", hist="exec.wave_mss")', "exec.wave_mss"),
])
def test_ka013_trips_on_each_namespace(line, needle):
    src = f"def f(self):\n    {line}\n"
    findings = kalint.lint_source(src, "foo.py")
    ka013 = [f for f in findings if f.rule == "KA013"]
    assert len(ka013) == 1 and needle in ka013[0].message


def test_ka013_keyword_spelling_cannot_bypass():
    findings = kalint.lint_source(
        'def f():\n    counter_add(name="daemon.requestz")\n', "foo.py"
    )
    assert any(
        f.rule == "KA013" and "daemon.requestz" in f.message
        for f in findings
    )
    assert "KA013" not in rules_of(kalint.lint_source(
        'def f():\n    span(name="encode")\n', "foo.py"
    ))


def test_ka013_skips_dynamic_names():
    # Dynamic names are the REGISTERED composition points (cluster labels,
    # per-kind fault counters) — never findings.
    src = (
        "def f(self, ev, name):\n"
        '    counter_add(f"faults.injected.{ev.kind}")\n'
        "    counter_add(name)\n"
        "    span(self._metric('daemon/request'))\n"
    )
    assert "KA013" not in rules_of(kalint.lint_source(src, "foo.py"))


def test_ka013_spans_and_metrics_are_separate_namespaces():
    # A metric name handed to span() (or vice versa) is as wrong as a typo:
    # the registry split is the contract.
    findings = kalint.lint_source(
        'def f():\n    span("daemon.requests")\n', "foo.py"
    )
    assert any(f.rule == "KA013" for f in findings)
    findings = kalint.lint_source(
        'def f():\n    counter_add("daemon/request")\n', "foo.py"
    )
    assert any(f.rule == "KA013" for f in findings)


def test_ka013_suppressible_with_reason():
    src = (
        "def f():\n"
        "    # kalint: disable=KA013 -- third-party sink, not our registry\n"
        '    counter_add("vendor.custom.metric")\n'
    )
    assert "KA013" not in rules_of(kalint.lint_source(src, "foo.py"))


def test_ka013_registry_tables_are_disjoint_and_nonempty():
    from kafka_assigner_tpu.obs.names import (
        ALL_NAMES,
        METRIC_NAMES,
        SPAN_NAMES,
    )

    assert METRIC_NAMES and SPAN_NAMES
    assert not (METRIC_NAMES & SPAN_NAMES)
    assert ALL_NAMES == METRIC_NAMES | SPAN_NAMES


# --- KA014: every metric states its unit or is declared unitless -------------

def test_ka014_trips_on_suffixless_undeclared_metric():
    findings = kalint.check_metric_units(
        metric_names={"foo.latency"}, unitless=set(),
    )
    assert [f.rule for f in findings] == ["KA014"]
    assert "foo.latency" in findings[0].message
    assert "unit suffix" in findings[0].message


@pytest.mark.parametrize("name", [
    "foo.wait_ms", "zk.bytes", "io.read_bytes", "used.heap_frac",
    "foo.requests_total", "uptime.seconds",
])
def test_ka014_unit_suffixed_names_pass(name):
    assert kalint.check_metric_units(
        metric_names={name}, unitless=set(),
    ) == []


def test_ka014_unit_token_must_be_a_suffix_not_a_substring():
    # "bytes" mid-segment is NOT a unit statement (the grandfathered
    # zk.wire_bytes_in names are allowlisted precisely because of this)
    findings = kalint.check_metric_units(
        metric_names={"zk.wire_bytes_in"}, unitless=set(),
    )
    assert [f.rule for f in findings] == ["KA014"]
    assert kalint.check_metric_units(
        metric_names={"zk.wire_bytes_in"}, unitless={"zk.wire_bytes_in"},
    ) == []


def test_ka014_allowlisted_unitless_passes():
    assert kalint.check_metric_units(
        metric_names={"daemon.requests"}, unitless={"daemon.requests"},
    ) == []


def test_ka014_stale_allowlist_entry_is_a_finding():
    findings = kalint.check_metric_units(
        metric_names=set(), unitless={"ghost.metric"},
    )
    assert [f.rule for f in findings] == ["KA014"]
    assert "stale" in findings[0].message


def test_ka014_double_declared_name_is_a_finding():
    findings = kalint.check_metric_units(
        metric_names={"exec.wave_ms"}, unitless={"exec.wave_ms"},
    )
    assert [f.rule for f in findings] == ["KA014"]
    assert "pick one" in findings[0].message


def test_ka014_repo_registry_is_clean():
    """The live registry (obs/names.py METRIC_NAMES vs UNITLESS_METRICS)
    passes its own rule — the repo-wide sweep the lint gate runs."""
    assert kalint.check_metric_units() == []


# --- ISSUE 12: the project-wide resolution layer ------------------------------

from pathlib import Path as _Path

FIXTURES = _Path(__file__).resolve().parent / "kalint_fixtures"


def test_resolution_survives_import_cycles():
    project = kalint.build_project(FIXTURES / "miniproj")
    cg = project.call_graph
    # both halves of the a<->b cycle resolved through the cycle
    assert "cyc_b.py::pong" in cg["cyc_a.py::ping"]
    assert "cyc_a.py::ping" in cg["cyc_b.py::pong"]
    assert "cyc_b.py" in project.import_graph["cyc_a.py"]
    assert "cyc_a.py" in project.import_graph["cyc_b.py"]


def test_resolution_from_import_aliasing():
    project = kalint.build_project(FIXTURES / "miniproj")
    # `from .cyc_a import ping as renamed_ping` — the alias dispatches to
    # the aliased function, not to a phantom `renamed_ping`
    assert "cyc_a.py::ping" in project.call_graph["alias.py::caller"]


def test_resolution_method_vs_function():
    project = kalint.build_project(FIXTURES / "miniproj")
    both = project.call_graph["klass.py::Widget.both"]
    assert "klass.py::Widget.report" in both   # self.report() -> method
    assert "klass.py::report" in both          # report() -> module function
    use = project.call_graph["klass.py::use_widget"]
    assert "klass.py::Widget.__init__" in use  # constructor edge
    assert "klass.py::Widget.report" in use    # local `w = Widget()` typed


def test_two_hop_traced_chain_crosses_modules():
    project = kalint.build_project(FIXTURES / "miniproj")
    traced = kalint.traced_set(project)
    assert "leaf.py::sink" in traced.members
    keys = [k for k, _line in traced.chain("leaf.py::sink")]
    assert keys == ["entry.py::solve", "mid.py::helper", "leaf.py::sink"]


def test_lint_tree_reports_cross_module_ka002_with_chain():
    findings = kalint.lint_tree(FIXTURES / "miniproj")
    ka002 = [f for f in findings if f.rule == "KA002"]
    assert len(ka002) == 1
    f = ka002[0]
    assert f.path.endswith("leaf.py") and f.line == 6
    assert [hop.split("@")[0] for hop in f.chain] == [
        "entry.py::solve", "mid.py::helper", "leaf.py::sink",
    ]


# --- KA015/KA016/KA017/KA012-transitive: tmp-tree fixtures --------------------

def _write_tree(tmp_path, files):
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src, encoding="utf-8")
    return root


def test_ka015_blocking_sleep_reachable_under_solve_lock(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "util.py": (
            "import time\n\n\n"
            "def slow_help(x):\n"
            "    time.sleep(1)\n"
            "    return x\n"
        ),
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "import threading\n\n"
            "from ..util import slow_help\n\n\n"
            "class ClusterSupervisor:\n"
            "    def __init__(self):\n"
            "        self._solve_lock = threading.Lock()\n\n"
            "    def handle(self, x):\n"
            "        with self._solve_lock:\n"
            "            return slow_help(x)\n"
        ),
    })
    findings = kalint.lint_tree(root)
    ka015 = [f for f in findings if f.rule == "KA015"]
    assert len(ka015) == 1
    f = ka015[0]
    assert f.path.endswith("util.py") and "sleep" in f.message
    assert any("ClusterSupervisor.handle" in hop for hop in f.chain)


def test_ka015_blocking_call_outside_the_lock_is_clean(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "import threading\n"
            "import time\n\n\n"
            "class ClusterSupervisor:\n"
            "    def __init__(self):\n"
            "        self._solve_lock = threading.Lock()\n\n"
            "    def handle(self, x):\n"
            "        time.sleep(0.1)  # before taking the lock: legal\n"
            "        with self._solve_lock:\n"
            "            y = x + 1\n"
            "        time.sleep(0.1)  # after releasing: legal\n"
            "        return y\n"
        ),
    })
    assert "KA015" not in rules_of(kalint.lint_tree(root))


def test_ka015_direct_sink_inside_the_with_body(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/service.py": (
            "import threading\n"
            "import time\n\n"
            "_solve_lock = threading.Lock()\n\n\n"
            "def dispatch(x):\n"
            "    with _solve_lock:\n"
            "        time.sleep(1)\n"
            "        return x\n"
        ),
    })
    ka015 = [f for f in kalint.lint_tree(root) if f.rule == "KA015"]
    assert len(ka015) == 1 and ka015[0].line == 9  # the sleep line


def test_ka016_trace_time_knob_read_with_chain(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "kern.py": (
            "import jax\n\n"
            "from .cfg import chunk\n\n\n"
            "def f(x):\n"
            "    return x * chunk()\n\n\n"
            "f_jit = jax.jit(f)\n"
        ),
        "cfg.py": (
            "def chunk():\n"
            "    from ..utils.env import env_int\n"
            '    return env_int("KA_PLACE_CHUNK")\n'
        ),
    })
    findings = kalint.lint_tree(root)
    ka016 = [f for f in findings if f.rule == "KA016"]
    assert len(ka016) == 1
    f = ka016[0]
    assert f.path.endswith("cfg.py") and "KA_PLACE_CHUNK" in f.message
    assert [hop.split("@")[0] for hop in f.chain] == [
        "kern.py::f", "cfg.py::chunk",
    ]
    # the same accessor call OUTSIDE the traced set is legal
    assert not any(
        f.rule == "KA016" and f.path.endswith("kern.py") for f in findings
    )


def test_ka017_obs_write_in_traced_code(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "kern.py": (
            "import jax\n\n\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    from .obs.metrics import counter_add\n"
            '    counter_add("zk.reads")\n'
            "    return x\n"
        ),
    })
    ka017 = [f for f in kalint.lint_tree(root) if f.rule == "KA017"]
    assert len(ka017) == 1
    assert "counter_add" in ka017[0].message
    assert ka017[0].chain  # the chain names the jit entry


def test_ka012_transitive_handler_helper_backend_chain(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "helpers.py": (
            "from .daemon.supervisor import ClusterSupervisor\n\n\n"
            "def peek_backend(sup: ClusterSupervisor):\n"
            "    return sup.backend\n"
        ),
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "class ClusterSupervisor:\n"
            "    def __init__(self):\n"
            "        self.backend = object()\n"
        ),
        "daemon/service.py": (
            "from ..helpers import peek_backend\n"
            "from .supervisor import ClusterSupervisor\n\n\n"
            "def do_plan(sup: ClusterSupervisor):\n"
            "    return peek_backend(sup)\n"
        ),
    })
    findings = kalint.lint_tree(root)
    ka012 = [f for f in findings if f.rule == "KA012"]
    assert len(ka012) == 1
    f = ka012[0]
    assert f.path.endswith("helpers.py") and ".backend" in f.message
    assert any("daemon/service.py::do_plan" in hop for hop in f.chain)


def test_ka012_supervisor_itself_reading_backend_stays_legal(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "class ClusterSupervisor:\n"
            "    def __init__(self):\n"
            "        self.backend = object()\n\n"
            "    def brokers(self):\n"
            "        return self.backend\n"
        ),
    })
    assert "KA012" not in rules_of(kalint.lint_tree(root))


def test_ka029_daemon_handler_helper_jit_chain(tmp_path):
    # ISSUE 19: a daemon handler reaching a *_jit device dispatch through
    # a helper OUTSIDE the dispatcher seam bypasses the gather queue.
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "kern.py": (
            "def _build():\n"
            "    return lambda x: x\n\n\n"
            "place_scan_narrow_jit = _build()\n"
        ),
        "helpers.py": (
            "from .kern import place_scan_narrow_jit\n\n\n"
            "def fast_place(rows):\n"
            "    return place_scan_narrow_jit(rows)\n"
        ),
        "daemon/__init__.py": "",
        "daemon/service.py": (
            "from ..helpers import fast_place\n\n\n"
            "def handle_plan(rows):\n"
            "    return fast_place(rows)\n"
        ),
    })
    findings = kalint.lint_tree(root)
    ka029 = [f for f in findings if f.rule == "KA029"]
    assert len(ka029) == 1
    f = ka029[0]
    assert f.path.endswith("helpers.py")
    assert "place_scan_narrow_jit" in f.message
    assert any("daemon/service.py::handle_plan" in hop for hop in f.chain)


def test_ka029_direct_dispatch_and_store_entry_in_daemon_module(tmp_path):
    # Both sink shapes inside a daemon module itself: a *_jit call and a
    # store-backed _sweep_program entry acquisition.
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "kern.py": (
            "def _build():\n"
            "    return lambda x: x\n\n\n"
            "score_batched_jit = _build()\n"
        ),
        "parallel/__init__.py": "",
        "parallel/whatif.py": (
            "def _sweep_program(name):\n"
            "    return lambda block: block\n"
        ),
        "daemon/__init__.py": "",
        "daemon/controller.py": (
            "from ..kern import score_batched_jit\n"
            "from ..parallel.whatif import _sweep_program\n\n\n"
            "def tick(rows):\n"
            "    return score_batched_jit(rows)\n\n\n"
            "def hot_sweep(block):\n"
            '    return _sweep_program("whatif_sweep")(block)\n'
        ),
    })
    findings = kalint.lint_tree(root)
    ka029 = [f for f in findings if f.rule == "KA029"]
    assert len(ka029) == 2
    msgs = " ".join(f.message for f in ka029)
    assert "score_batched_jit" in msgs and "_sweep_program" in msgs
    assert all(f.path.endswith("daemon/controller.py") for f in ka029)


def test_ka029_clean_when_the_chain_passes_through_the_seam(tmp_path):
    # The sanctioned shape: the handler reaches the device only through a
    # bucket-boundary module (traversal stops AT the seam, and the seam's
    # own *_jit dispatches are its business). wrap_jit is a program
    # BUILDER, not a dispatch, and stays legal anywhere.
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "kern.py": (
            "def _build():\n"
            "    return lambda x: x\n\n\n"
            "place_scan_narrow_jit = _build()\n"
        ),
        "util.py": (
            "def wrap_jit(name, fn):\n"
            "    return fn\n"
        ),
        "solvers/__init__.py": "",
        "solvers/tpu.py": (
            "from ..kern import place_scan_narrow_jit\n\n\n"
            "def assign_many(rows):\n"
            "    return place_scan_narrow_jit(rows)\n"
        ),
        "daemon/__init__.py": "",
        "daemon/service.py": (
            "from ..solvers.tpu import assign_many\n"
            "from ..util import wrap_jit\n\n\n"
            "def handle_plan(rows):\n"
            "    return assign_many(rows)\n\n\n"
            "def warm(fn):\n"
            '    return wrap_jit("warm", fn)\n'
        ),
    })
    assert "KA029" not in rules_of(kalint.lint_tree(root))


# --- suppressions on wrapped (multi-line) statements --------------------------

def test_suppression_on_last_line_of_wrapped_call():
    src = (
        "import json\n"
        "\n"
        "def emit(d):\n"
        "    return json.dumps(\n"
        "        d,\n"
        "        sort_keys=True,\n"
        "    )  # kalint: disable=KA005 -- fixture payload\n"
    )
    assert kalint.lint_source(src, "generator.py") == []


def test_suppression_on_middle_line_of_wrapped_call():
    src = (
        "import json\n"
        "\n"
        "def emit(d):\n"
        "    return json.dumps(\n"
        "        d,  # kalint: disable=KA005 -- fixture payload\n"
        "        sort_keys=True,\n"
        "    )\n"
    )
    assert kalint.lint_source(src, "generator.py") == []


def test_suppression_inside_a_block_does_not_leak_to_the_header():
    # a suppression on a statement INSIDE a while body must not suppress a
    # finding anchored on the while header itself
    src = (
        "def pump(sock):\n"
        "    while True:\n"
        "        data = sock.recv(4)  # kalint: disable=KA011 -- wrong line\n"
    )
    assert "KA011" in rules_of(kalint.lint_source(src, "io/foo.py"))


def test_wrapped_statement_span_does_not_cover_unrelated_lines():
    # the suppression rides the wrapped statement it is ON, not statements
    # further down (the legacy rule still covers the line DIRECTLY below,
    # so the second call sits two lines later)
    src = (
        "import json\n"
        "\n"
        "def emit(d):\n"
        "    a = json.dumps(\n"
        "        d,\n"
        "    )  # kalint: disable=KA005 -- first call only\n"
        "\n"
        "    b = json.dumps(d)\n"
        "    return a, b\n"
    )
    findings = kalint.lint_source(src, "generator.py")
    assert [f.line for f in findings if f.rule == "KA005"] == [8]


# --- deterministic output: sort + dedupe --------------------------------------

def test_finalize_sorts_by_path_line_rule_and_dedupes():
    f_dup_a = kalint.Finding("KA005", "b.py", 3, 1, "per-module twin")
    f_other = kalint.Finding("KA001", "a.py", 9, 1, "other file")
    f_chain = kalint.Finding("KA005", "b.py", 3, 1, "graph twin",
                             chain=("m.py::f@1",))
    out = kalint.finalize([f_dup_a, f_other, f_chain])
    assert [(f.path, f.line, f.rule) for f in out] == [
        ("a.py", 9, "KA001"), ("b.py", 3, "KA005"),
    ]
    # the chain-bearing twin wins the dedupe (it carries the why)
    assert out[1].chain == ("m.py::f@1",)


def test_finalize_keeps_distinct_sinks_sharing_a_line():
    # two different violations on one physical line (different columns)
    # are NOT duplicates — only same-node twins merge
    f_a = kalint.Finding("KA002", "k.py", 5, 12, "time.time() ...")
    f_b = kalint.Finding("KA002", "k.py", 5, 26, "time.perf_counter() ...")
    assert len(kalint.finalize([f_a, f_b])) == 2


def test_lint_source_output_is_sorted():
    src = (
        "import os, json\n"
        "def f():\n"
        '    v = os.environ.get("KA_TYPO_ONE")\n'
        "    return json.dumps(v)\n"
    )
    findings = kalint.lint_source(src, "foo.py")
    keys = [(f.path, f.line, f.rule) for f in findings]
    assert keys == sorted(keys)


# --- KA011: one-hop helper deadline resolution --------------------------------

def test_ka011_deadline_in_same_class_helper_is_honored():
    src = (
        "class Client:\n"
        "    def _deadline_remaining(self):\n"
        "        from ..utils.env import env_float\n"
        '        return env_float("KA_EXEC_POLL_TIMEOUT")\n'
        "\n"
        "    def pump(self, sock):\n"
        "        while True:\n"
        "            if self._deadline_remaining() <= 0:\n"
        "                break\n"
        "            data = sock.recv(4)\n"
    )
    assert "KA011" not in rules_of(kalint.lint_source(src, "io/foo.py"))


def test_ka011_deadline_in_same_module_function_helper_is_honored():
    src = (
        "def remaining():\n"
        "    from ..utils.env import env_float\n"
        '    return env_float("KA_EXEC_POLL_TIMEOUT")\n'
        "\n"
        "def pump(sock):\n"
        "    while True:\n"
        "        if remaining() <= 0:\n"
        "            break\n"
        "        data = sock.recv(4)\n"
    )
    assert "KA011" not in rules_of(kalint.lint_source(src, "io/foo.py"))


def test_ka011_two_hops_of_indirection_still_flagged():
    # ONE hop is the contract: the bound must stay near the loop
    src = (
        "def inner():\n"
        "    from ..utils.env import env_float\n"
        '    return env_float("KA_EXEC_POLL_TIMEOUT")\n'
        "\n"
        "def outer():\n"
        "    return inner()\n"
        "\n"
        "def pump(sock):\n"
        "    while True:\n"
        "        if outer() <= 0:\n"
        "            break\n"
        "        data = sock.recv(4)\n"
    )
    assert "KA011" in rules_of(kalint.lint_source(src, "io/foo.py"))


def test_ka011_helper_without_deadline_still_flagged():
    src = (
        "class Client:\n"
        "    def _helper(self):\n"
        "        return 1\n"
        "\n"
        "    def pump(self, sock):\n"
        "        while True:\n"
        "            self._helper()\n"
        "            data = sock.recv(4)\n"
    )
    assert "KA011" in rules_of(kalint.lint_source(src, "io/foo.py"))


# --- rule catalog / ruledoc ---------------------------------------------------

def test_rule_docs_cover_every_rule():
    assert set(kalint.RULE_DOCS) == set(kalint.RULES)
    assert set(kalint.RULES) == {f"KA{n:03d}" for n in range(31)}
    for rule, (meaning, example) in kalint.RULE_DOCS.items():
        assert meaning and example, rule


def test_ruledoc_renders_and_detects_drift():
    from kafka_assigner_tpu.analysis import ruledoc

    table = ruledoc.render_table()
    for rule in kalint.RULES:
        assert f"| {rule} |" in table
    fresh = ruledoc.apply(
        f"head\n{ruledoc.BEGIN_MARK}\nOLDCONTENT\n{ruledoc.END_MARK}\ntail\n"
    )
    assert table in fresh and "OLDCONTENT" not in fresh
    with pytest.raises(ValueError, match="markers"):
        ruledoc.apply("no markers here")


def test_ka015_sibling_with_item_entered_under_the_lock(tmp_path):
    # `with self._solve_lock, self.slow_setup():` — the second context
    # manager ENTERS while the lock is held, so its blocking work is
    # in scope; a manager listed BEFORE the lock enters first and is not
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "import threading\n"
            "import time\n\n\n"
            "class ClusterSupervisor:\n"
            "    def __init__(self):\n"
            "        self._solve_lock = threading.Lock()\n\n"
            "    def slow_setup(self):\n"
            "        time.sleep(5)\n\n"
            "    def quick_setup(self):\n"
            "        return self\n\n"
            "    def handle(self, x):\n"
            "        with self.quick_setup(), self._solve_lock, \\\n"
            "                self.slow_setup():\n"
            "            return x\n"
        ),
    })
    ka015 = [f for f in kalint.lint_tree(root) if f.rule == "KA015"]
    assert len(ka015) == 1 and "sleep" in ka015[0].message
    assert any("slow_setup" in hop for hop in ka015[0].chain)


# --- KA019: blocking work while an inflight-gate admission is held -----------

def test_ka019_blocking_sleep_after_gate_admission(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "util.py": (
            "import time\n\n\n"
            "def slow_help(x):\n"
            "    time.sleep(1)\n"
            "    return x\n"
        ),
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "from ..util import slow_help\n\n\n"
            "class ClusterSupervisor:\n"
            "    def _gate(self):\n"
            "        return None\n\n"
            "    def _release(self):\n"
            "        pass\n\n"
            "    def handle(self, x):\n"
            "        refusal = self._gate()\n"
            "        if refusal is not None:\n"
            "            return refusal\n"
            "        try:\n"
            "            return slow_help(x)\n"
            "        finally:\n"
            "            self._release()\n"
        ),
    })
    ka019 = [f for f in kalint.lint_tree(root) if f.rule == "KA019"]
    assert len(ka019) == 1
    f = ka019[0]
    assert f.path.endswith("util.py") and "sleep" in f.message
    assert "inflight-gate" in f.message
    assert any("ClusterSupervisor.handle" in hop for hop in f.chain)


def test_ka019_blocking_before_the_gate_is_clean(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "import time\n\n\n"
            "class ClusterSupervisor:\n"
            "    def _gate(self):\n"
            "        return None\n\n"
            "    def handle(self, x):\n"
            "        time.sleep(0.1)  # pre-admission wait: legal\n"
            "        refusal = self._gate()\n"
            "        return refusal\n"
        ),
    })
    assert "KA019" not in rules_of(kalint.lint_tree(root))


def test_ka019_direct_sink_after_gate_in_same_block(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/service.py": (
            "import time\n\n\n"
            "class Gatekeeper:\n"
            "    def _gate(self):\n"
            "        return None\n\n"
            "    def serve(self, x):\n"
            "        self._gate()\n"
            "        time.sleep(1)\n"
            "        return x\n"
        ),
    })
    ka019 = [f for f in kalint.lint_tree(root) if f.rule == "KA019"]
    assert len(ka019) == 1 and ka019[0].line == 10  # the sleep line


def test_ka019_outside_daemon_package_is_clean(tmp_path):
    # The gate discipline is a daemon/ house rule; other packages may
    # name a method _gate without adopting it.
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "other.py": (
            "import time\n\n\n"
            "class Thing:\n"
            "    def _gate(self):\n"
            "        return None\n\n"
            "    def run(self):\n"
            "        self._gate()\n"
            "        time.sleep(1)\n"
        ),
    })
    assert "KA019" not in rules_of(kalint.lint_tree(root))


def test_ka019_repo_chain_is_suppressed_with_reasons():
    # The one sanctioned blocking chain (the first-use lazy native build)
    # must stay suppressed for BOTH the lock rule and its gate twin.
    findings = kalint.lint_package(use_cache=False)
    assert not [f for f in findings if f.rule in ("KA015", "KA019")]


# --- KA018: dead-knob detection ---------------------------------------------

_ENV_FIXTURE = (
    "KNOBS = {}\n\n\n"
    "def _knob(name, type_, default):\n"
    "    KNOBS[name] = (type_, default)\n\n\n"
    '_knob("KA_LIVE_KNOB", "int", 1)\n'
    '_knob("KA_DEFAULTED_KNOB", "int", 2)\n'
    '_knob("KA_DEAD_KNOB", "int", 3)\n'
)


def _dead_knob_findings(reader_src):
    import ast as _ast

    trees = {
        "utils/env.py": _ast.parse(_ENV_FIXTURE),
        "consumer.py": _ast.parse(reader_src),
    }
    return kalint.check_dead_knobs(
        trees,
        knobs=["KA_LIVE_KNOB", "KA_DEFAULTED_KNOB", "KA_DEAD_KNOB"],
    )


def test_ka018_flags_only_the_never_read_knob():
    findings = _dead_knob_findings(
        "from .utils.env import env_int, knob_default\n\n\n"
        "def f():\n"
        '    return env_int("KA_LIVE_KNOB")\n\n\n'
        "def g():\n"
        '    return knob_default("KA_DEFAULTED_KNOB")\n'
    )
    assert [f.rule for f in findings] == ["KA018"]
    f = findings[0]
    assert "KA_DEAD_KNOB" in f.message
    # Anchored at the registration call in the registry module.
    assert f.path == "utils/env.py" and f.line == 10


def test_ka018_registration_is_not_a_read():
    # Nothing reads anything: every registered knob is dead, and the
    # _knob(...) registrations themselves must not count as reads.
    findings = _dead_knob_findings("X = 1\n")
    assert sorted(
        k for f in findings for k in (
            "KA_LIVE_KNOB", "KA_DEFAULTED_KNOB", "KA_DEAD_KNOB",
        ) if k in f.message
    ) == ["KA_DEAD_KNOB", "KA_DEFAULTED_KNOB", "KA_LIVE_KNOB"]


def test_ka018_read_inside_the_registry_module_does_not_count():
    import ast as _ast

    trees = {
        "utils/env.py": _ast.parse(
            _ENV_FIXTURE + '\n\ndef self_read():\n'
            '    return KNOBS["KA_DEAD_KNOB"]\n'
        ),
    }
    findings = kalint.check_dead_knobs(trees, knobs=["KA_DEAD_KNOB"])
    assert [f.rule for f in findings] == ["KA018"]


def test_ka018_repo_sweep_is_clean():
    # Every knob the live registry declares is read somewhere in the
    # package — the sweep that now gates tier-1 via lint_package.
    findings = kalint.lint_package(use_cache=False)
    assert not [f for f in findings if f.rule == "KA018"]


def test_ka018_and_ka019_are_documented():
    for rule in ("KA018", "KA019"):
        assert rule in kalint.RULES
        assert rule in kalint.RULE_DOCS


# --- KA020: the blocking-call budget (KA015/KA019's quantitative twin) -------

def test_ka020_gate_chain_exceeding_budget_flags(tmp_path):
    # KA_EXEC_POLL_TIMEOUT defaults to 600 s — one consult under an
    # admission blows the 30 s watchdog budget 20x over.
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "util.py": (
            "def poll_loop(env_float):\n"
            '    t = env_float("KA_EXEC_POLL_TIMEOUT")\n'
            "    return t\n"
        ),
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "from ..util import poll_loop\n\n\n"
            "class ClusterSupervisor:\n"
            "    def _gate(self):\n"
            "        return None\n\n"
            "    def handle(self, env_float):\n"
            "        refusal = self._gate()\n"
            "        if refusal is not None:\n"
            "            return refusal\n"
            "        return poll_loop(env_float)\n"
        ),
    })
    ka020 = [f for f in kalint.lint_tree(root) if f.rule == "KA020"]
    assert len(ka020) == 1
    f = ka020[0]
    assert f.path.endswith("util.py")
    assert "KA_EXEC_POLL_TIMEOUT" in f.message
    assert "600" in f.message and "30" in f.message
    assert any("ClusterSupervisor.handle" in hop for hop in f.chain)


def test_ka020_within_budget_is_clean(tmp_path):
    # KA_DAEMON_DRAIN_TIMEOUT defaults to 10 s: inside the 30 s budget.
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "class ClusterSupervisor:\n"
            "    def _gate(self):\n"
            "        return None\n\n"
            "    def handle(self, env_float):\n"
            "        self._gate()\n"
            '        return env_float("KA_DAEMON_DRAIN_TIMEOUT")\n'
        ),
    })
    assert "KA020" not in rules_of(kalint.lint_tree(root))


def test_ka020_retries_multiply_the_timeout(tmp_path):
    # 10 s drain timeout alone is fine; consulted NEXT TO a retries knob
    # (KA_ZK_CONNECT_RETRIES default 3) the worst case is 10 * (1+3) =
    # 40 s > 30 s — each retry re-arms the timeout.
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "class ClusterSupervisor:\n"
            "    def _gate(self):\n"
            "        return None\n\n"
            "    def handle(self, env_float, env_int):\n"
            "        self._gate()\n"
            '        n = env_int("KA_ZK_CONNECT_RETRIES")\n'
            '        t = env_float("KA_DAEMON_DRAIN_TIMEOUT")\n'
            "        return n * t\n"
        ),
    })
    ka020 = [f for f in kalint.lint_tree(root) if f.rule == "KA020"]
    assert len(ka020) == 1
    assert "KA_ZK_CONNECT_RETRIES" in ka020[0].message
    assert "40" in ka020[0].message


def test_ka020_solve_lock_chain_flags_too(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/service.py": (
            "import threading\n\n\n"
            "class Daemon:\n"
            "    def __init__(self):\n"
            "        self._solve_lock = threading.Lock()\n\n"
            "    def converge(self, env_float):\n"
            '        return env_float("KA_EXEC_POLL_TIMEOUT")\n\n'
            "    def serve(self, env_float):\n"
            "        with self._solve_lock:\n"
            "            return self.converge(env_float)\n"
        ),
    })
    ka020 = [f for f in kalint.lint_tree(root) if f.rule == "KA020"]
    assert len(ka020) == 1
    assert "solve lock" in ka020[0].message


def test_ka020_envelope_sums_across_chain_hops(tmp_path):
    # Each hop is under budget alone; the CHAIN is not — the rule prices
    # the path, not the function. Custom defaults via the public API.
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "class ClusterSupervisor:\n"
            "    def _gate(self):\n"
            "        return None\n\n"
            "    def first(self, env_float):\n"
            '        t = env_float("KA_HOP_TIMEOUT")\n'
            "        return self.second(env_float) + t\n\n"
            "    def second(self, env_float):\n"
            '        return env_float("KA_HOP_TIMEOUT")\n\n'
            "    def handle(self, env_float):\n"
            "        self._gate()\n"
            "        return self.first(env_float)\n"
        ),
    })
    project = kalint.build_project(root)
    defaults = {"KA_HOP_TIMEOUT": 4.0, kalint.BUDGET_KNOB: 6.0}
    findings = kalint.check_blocking_budget(project, {}, defaults)
    # `second`'s chain is handle -> first (4s) -> second (4s) = 8s > 6s;
    # `first` alone is 4s and stays clean.
    assert [f.rule for f in findings] == ["KA020"]
    assert "8 s" in findings[0].message


def test_ka020_ms_knobs_price_as_milliseconds(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "class ClusterSupervisor:\n"
            "    def _gate(self):\n"
            "        return None\n\n"
            "    def handle(self, env_float):\n"
            "        self._gate()\n"
            '        return env_float("KA_GATHER_TIMEOUT_MS")\n'
        ),
    })
    project = kalint.build_project(root)
    # 5000 ms = 5 s: under a 6 s budget despite the large raw number.
    assert kalint.check_blocking_budget(
        project, {},
        {"KA_GATHER_TIMEOUT_MS": 5000.0, kalint.BUDGET_KNOB: 6.0},
    ) == []
    # 9000 ms = 9 s: over it.
    flagged = kalint.check_blocking_budget(
        project, {},
        {"KA_GATHER_TIMEOUT_MS": 9000.0, kalint.BUDGET_KNOB: 6.0},
    )
    assert [f.rule for f in flagged] == ["KA020"]


def test_ka020_suppression_with_reason(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/supervisor.py": (
            "class ClusterSupervisor:\n"
            "    def _gate(self):\n"
            "        return None\n\n"
            "    def handle(self, env_float):  # kalint: disable=KA020 -- bound unreachable: the poll exits on the drain event first\n"
            "        self._gate()\n"
            '        return env_float("KA_EXEC_POLL_TIMEOUT")\n'
        ),
    })
    assert "KA020" not in rules_of(kalint.lint_tree(root))


def test_ka020_repo_sweep_is_clean():
    # The repo's own held regions price under the watchdog budget: every
    # long-deadline consult (exec convergence polls, connect retries)
    # lives OUTSIDE the solve lock and the admission gates — the
    # controller's act path (ISSUE 15) deliberately executes after
    # releasing its evaluation slot for exactly this reason.
    findings = kalint.lint_package(use_cache=False)
    assert not [f for f in findings if f.rule == "KA020"]


def test_ka020_is_documented():
    assert "KA020" in kalint.RULES
    assert "KA020" in kalint.RULE_DOCS


# --- ISSUE 16: thread topology, shared state, KA021/KA022/KA023 ---------------

import json as _json
import os as _os
import shutil as _shutil

THREADS = FIXTURES / "threads"


def test_thread_entry_discovery_forms():
    project = kalint.build_project(THREADS)
    entries = {e.key: e for e in kalint.discover_thread_entries(project)}
    assert entries["daemon/worker.py::Worker._loop"].kind == "thread"
    assert entries["daemon/worker.py::Worker._tick"].kind == "timer"
    assert entries["daemon/worker.py::Worker._work"].kind == "executor"
    # the closure-nested target is invisible to the resolver: NO entry
    # (under-approximation, same posture as the resolver itself)
    assert len(entries) == 3
    loop = entries["daemon/worker.py::Worker._loop"]
    assert "'loop'" in loop.label and "daemon/worker.py:19" in loop.label
    assert not loop.concurrent


def test_thread_model_pins_the_real_daemon_topology():
    root = _Path(kalint.__file__).resolve().parents[2]
    model = kalint.thread_model(kalint.build_project(root))
    keys = {e.key for e in model.entries}
    assert "daemon/supervisor.py::ClusterSupervisor._watch_loop" in keys
    assert "daemon/controller.py::RebalanceController._loop" in keys
    assert "daemon/dispatch.py::SolveDispatcher._loop" in keys
    assert "daemon/supervisor.py::ClusterSupervisor.handle" in keys
    # the HTTP surface races with itself: one thread per connection
    assert all(e.concurrent for e in model.entries if e.kind == "http")
    assert not any(e.concurrent for e in model.entries if e.kind == "main")
    # lock-set inference generalized beyond the solve lock (KA015's one
    # special case): the whole registry is discovered by name
    assert {"_solve_lock", "_mutex", "_counters_lock"} <= set(model.locks)


def test_lock_set_inference_lexical_and_must_hold():
    model = kalint.thread_model(kalint.build_project(THREADS))
    accs = {(a.funckey, a.attr, a.write): sorted(a.locks)
            for a in model.accesses}
    # lexical: _tick writes count inside `with self._lock`
    assert accs[("daemon/worker.py::Worker._tick", "count", True)] \
        == ["_lock"]
    # MUST-hold: _bump has no `with` in sight — the lock is credited
    # because its only reaching call site (in _loop) holds it
    assert accs[("daemon/worker.py::Worker._bump", "count", True)] \
        == ["_lock"]
    # the forgotten path: _work reads count with nothing held
    assert accs[("daemon/worker.py::Worker._work", "count", False)] == []


def test_ka021_ka022_ka023_on_the_threads_fixture():
    findings = kalint.lint_tree(THREADS)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    assert set(by_rule) == {"KA021", "KA022", "KA023"}
    (ka021,) = by_rule["KA021"]
    assert ka021.path.endswith("worker.py")
    assert "Worker.flag" in ka021.message
    assert "empty common lock-set" in ka021.message
    assert "thread 'loop' entry" in ka021.message
    assert ka021.chain[0].startswith("daemon/worker.py::Worker._loop@")
    (ka022,) = by_rule["KA022"]
    assert "Worker.count" in ka022.message
    assert "guarded by _lock on every write" in ka022.message
    assert "read here with no common lock held" in ka022.message
    (ka023,) = by_rule["KA023"]
    assert "lock-order cycle _alock -> _block -> _alock" in ka023.message
    assert "deadlock" in ka023.message


def test_thread_rules_clean_when_guarded_consistently(tmp_path):
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/worker.py": (
            "import threading\n\n\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.flag = False\n\n"
            "    def start(self, pool):\n"
            "        threading.Thread(target=self._loop).start()\n"
            "        pool.submit(self._work)\n\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self.flag = True\n\n"
            "    def _work(self):\n"
            "        with self._lock:\n"
            "            self.flag = False\n"
        ),
    })
    assert not rules_of(kalint.lint_tree(root)) & {
        "KA021", "KA022", "KA023"}


def test_single_writer_published_flag_is_a_non_goal(tmp_path):
    # one loop publishing, another thread only READING: the deliberate
    # non-goal (flagging it would drown triage in benign poll patterns)
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/worker.py": (
            "import threading\n\n\n"
            "class Worker:\n"
            "    def start(self, pool):\n"
            "        threading.Thread(target=self._loop).start()\n"
            "        pool.submit(self._watch)\n\n"
            "    def _loop(self):\n"
            "        self.done = True\n\n"
            "    def _watch(self):\n"
            "        return self.done\n"
        ),
    })
    assert not rules_of(kalint.lint_tree(root)) & {"KA021", "KA022"}


def test_thread_rule_suppressions_with_reasons(tmp_path):
    src = (THREADS / "daemon" / "worker.py").read_text(encoding="utf-8")
    src = src.replace(
        "        self.flag = True\n",
        "        self.flag = True  # kalint: disable=KA021 -- fixture: "
        "the start/join handoff protocol serializes the writers\n")
    src = src.replace(
        "        return self.count\n",
        "        return self.count  # kalint: disable=KA022 -- fixture: "
        "torn read tolerated, the value is advisory\n")
    src = src.replace(
        "        with self._alock:\n            with self._block:",
        "        with self._alock:  # kalint: disable=KA023 -- fixture: "
        "backward() only runs during single-threaded shutdown\n"
        "            with self._block:  # kalint: disable=KA023 -- "
        "fixture: same shutdown protocol\n")
    root = _write_tree(tmp_path, {
        "__init__.py": "",
        "daemon/__init__.py": "",
        "daemon/worker.py": src,
    })
    assert not rules_of(kalint.lint_tree(root)) & {
        "KA021", "KA022", "KA023"}


def test_thread_rules_repo_sweep_is_clean():
    # The ISSUE 16 triage landed: the controller ledger double-load race
    # was REAL (fixed: double-checked load under _mutex, snapshot in
    # _save_ledger); the surviving benign patterns (lifecycle dedup
    # flag, GIL-atomic monitoring reads, the _prompt_resync handoff
    # bool) are reason-suppressed at their sites with the thread/lock
    # chain cited.
    findings = kalint.lint_package(use_cache=False)
    assert not [f for f in findings
                if f.rule in ("KA021", "KA022", "KA023")]


def test_thread_rules_are_documented():
    for rule in ("KA021", "KA022", "KA023"):
        assert rule in kalint.RULES and rule in kalint.RULE_DOCS


# --- KA020 controller-loop extension ------------------------------------------

CONTROLLER_TREE = {
    "__init__.py": "",
    "util.py": (
        "def converge(env_float):\n"
        '    return env_float("KA_EXEC_POLL_TIMEOUT")\n'
    ),
    "daemon/__init__.py": "",
    "daemon/controller.py": (
        "import threading\n\n"
        "from ..util import converge\n\n\n"
        "class Controller:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop).start()\n\n"
        "    def _loop(self, env_float):\n"
        "        return converge(env_float)\n"
    ),
}


def test_ka020_controller_loop_priced_against_interval(tmp_path):
    # the exec-engine poll budget (600 s) consulted ON the controller
    # loop thread blows one 30 s loop interval 20x over
    root = _write_tree(tmp_path, CONTROLLER_TREE)
    ka020 = [f for f in kalint.lint_tree(root) if f.rule == "KA020"]
    assert len(ka020) == 1
    f = ka020[0]
    assert f.path.endswith("util.py")
    assert "controller loop" in f.message
    assert "KA_CONTROLLER_INTERVAL" in f.message
    assert "600" in f.message and "30" in f.message
    assert any("Controller._loop" in hop for hop in f.chain)


def test_ka020_controller_budget_knob_is_the_dial(tmp_path):
    root = _write_tree(tmp_path, CONTROLLER_TREE)
    project = kalint.build_project(root)
    flagged = kalint.check_blocking_budget(project, {}, {
        "KA_EXEC_POLL_TIMEOUT": 600.0,
        kalint.CONTROLLER_BUDGET_KNOB: 30.0,
    })
    assert [f.rule for f in flagged] == ["KA020"]
    # a slower loop cadence absorbs the same envelope
    assert kalint.check_blocking_budget(project, {}, {
        "KA_EXEC_POLL_TIMEOUT": 600.0,
        kalint.CONTROLLER_BUDGET_KNOB: 1200.0,
    }) == []


# --- cross-process taint: the smoke harnesses in the project graph ------------

def test_smoke_scripts_resolved_into_the_project_graph():
    from kafka_assigner_tpu.analysis.kalint import driver
    root = _Path(kalint.__file__).resolve().parents[2]
    smokes = driver._smoke_scripts(root.parent)
    assert ("scripts/daemon_smoke.py" in {rel for rel, _ in smokes})
    project = kalint.build_project(root, extra_modules=smokes)
    assert "scripts" in project.extra_tops
    assert "scripts/exec_smoke.py" in project.modules
    # the harness plumbing resolves INTO the package: cross-process
    # taint, not an island
    cross = set()
    for key, callees in project.call_graph.items():
        if key.startswith("scripts/"):
            cross |= {c for c in callees if not c.startswith("scripts/")}
    assert "faults/inject.py::reset" in cross
    assert len(cross) >= 5


def test_smoke_scripts_swept_by_the_package_lint():
    # scripts/ modules ride through lint_package with the travelling
    # hygiene rules; their suppressions carry reasons like everyone
    # else's — the sweep stays clean
    findings = kalint.lint_package(use_cache=False)
    assert not [f for f in findings if f.path.startswith("scripts/")]


# --- SARIF output and --changed-only ------------------------------------------

SARIF_MINI_SCHEMA = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "type": "object",
    "required": ["version", "runs"],
    "properties": {
        "version": {"const": "2.1.0"},
        "runs": {
            "type": "array", "minItems": 1,
            "items": {
                "type": "object",
                "required": ["tool", "results"],
                "properties": {
                    "tool": {
                        "type": "object", "required": ["driver"],
                        "properties": {"driver": {
                            "type": "object", "required": ["name"],
                            "properties": {
                                "name": {"type": "string"},
                                "rules": {"type": "array", "items": {
                                    "type": "object",
                                    "required": ["id"],
                                }},
                            },
                        }},
                    },
                    "results": {"type": "array", "items": {
                        "type": "object",
                        "required": ["ruleId", "message", "locations"],
                        "properties": {
                            "ruleId": {"type": "string"},
                            "level": {"enum": [
                                "none", "note", "warning", "error"]},
                            "message": {
                                "type": "object", "required": ["text"]},
                            "locations": {
                                "type": "array", "minItems": 1},
                            "codeFlows": {"type": "array", "items": {
                                "type": "object",
                                "required": ["threadFlows"],
                            }},
                        },
                    }},
                },
            },
        },
    },
}


def test_sarif_output_validates_and_carries_thread_flows(tmp_path):
    out = tmp_path / "kalint.sarif"
    rc = kalint.main(["--root", str(THREADS), "--no-cache",
                      "--format", "sarif", "--out", str(out)])
    assert rc == 1
    payload = _json.loads(out.read_text(encoding="utf-8"))
    assert payload["version"] == "2.1.0"
    assert payload["$schema"].endswith("sarif-schema-2.1.0.json")
    run = payload["runs"][0]
    assert run["tool"]["driver"]["name"] == "kalint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rule_ids == set(kalint.RULES)
    results = run["results"]
    assert {r["ruleId"] for r in results} == {"KA021", "KA022", "KA023"}
    ka021 = next(r for r in results if r["ruleId"] == "KA021")
    loc = ka021["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("worker.py")
    assert loc["region"]["startLine"] >= 1
    flow = ka021["codeFlows"][0]["threadFlows"][0]["locations"]
    assert flow[0]["location"]["message"]["text"].startswith(
        "daemon/worker.py::Worker._loop@")
    jsonschema = pytest.importorskip("jsonschema")
    jsonschema.validate(payload, SARIF_MINI_SCHEMA)


def test_sarif_and_json_reports_are_deterministic(tmp_path):
    a, b = tmp_path / "a.sarif", tmp_path / "b.sarif"
    for out in (a, b):
        kalint.main(["--root", str(THREADS), "--no-cache",
                     "--format", "sarif", "--out", str(out)])
    assert a.read_text() == b.read_text()


def test_explain_ka021_prints_the_thread_chain(capsys):
    rc = kalint.main(["--root", str(THREADS), "--no-cache",
                      "--explain", "KA021"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "KA021 at" in out and "chain:" in out
    # the chain roots at the thread entry and ends at the unguarded write
    assert "daemon/worker.py::Worker._loop@" in out


def test_changed_only_unit_filter(tmp_path):
    from kafka_assigner_tpu.analysis.kalint import cli as klcli
    old, new = tmp_path / "old.py", tmp_path / "new.py"
    old.write_text("x = 1\n")
    new.write_text("y = 2\n")
    _os.utime(old, (1000.0, 1000.0))
    _os.utime(new, (2000.0, 2000.0))
    findings = [kalint.Finding("KA001", "old.py", 1, 1, "m"),
                kalint.Finding("KA001", "new.py", 1, 1, "m"),
                kalint.Finding("KA001", "gone.py", 1, 1, "m")]
    kept = klcli._changed_only(findings, tmp_path, 1500.0)
    # stale file dropped; fresh file kept; unstattable path NEVER hidden
    assert [f.path for f in kept] == ["new.py", "gone.py"]
    # no baseline (cold/disabled cache): restriction must be a no-op
    assert klcli._changed_only(findings, tmp_path, None) == findings


def test_changed_only_end_to_end_with_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_LINT_CACHE", "1")
    monkeypatch.setenv("KA_LINT_CACHE_DIR", str(tmp_path / "cache"))
    pkg = tmp_path / "pkg"
    _shutil.copytree(THREADS, pkg)
    out = tmp_path / "r.json"
    args = ["--root", str(pkg), "--format", "json", "--changed-only",
            "--out", str(out)]
    # cold cache: no baseline — every finding is kept
    assert kalint.main(args) == 1
    assert _json.loads(out.read_text())["count"] == 3
    # warm, nothing touched since the entry: the REPORT is empty (the
    # analysis still ran whole-tree — this is a report restriction)
    assert kalint.main(args) == 0
    assert _json.loads(out.read_text())["count"] == 0
    # touch one file into the future — relative to NOW, not its copytree-
    # preserved mtime, which is as old as the checkout (content unchanged:
    # still a cache hit) — its findings come back
    import time as _time

    worker = pkg / "daemon" / "worker.py"
    st = worker.stat()
    _os.utime(worker, (st.st_atime, _time.time() + 3600))
    assert kalint.main(args) == 1
    assert _json.loads(out.read_text())["count"] == 3
    # ISSUE 17: a git checkout REWINDS mtimes — the mtime-vs-baseline
    # test alone would hide exactly the files the checkout changed, so
    # --changed-only must also keep everything `git status` calls dirty.
    import subprocess as _sp

    def _git(*argv):
        _sp.run(["git", "-C", str(tmp_path),
                 "-c", "user.email=t@t", "-c", "user.name=t", *argv],
                check=True, capture_output=True)

    _git("init", "-q")
    _git("add", "-A")
    _git("commit", "-q", "-m", "baseline")
    # clean per git AND stale per mtime: the report restriction holds
    st = worker.stat()
    _os.utime(worker, (st.st_atime, st.st_mtime - 7200))
    assert kalint.main(args) == 0
    assert _json.loads(out.read_text())["count"] == 0
    # a simulated checkout: content changes but the mtime lands in the
    # PAST — git's view (modified) must bring the findings back even
    # though the mtime test says "unchanged"
    worker.write_text(worker.read_text() + "# checked-out variant\n",
                      encoding="utf-8")
    st = worker.stat()
    _os.utime(worker, (st.st_atime, st.st_mtime - 7200))
    assert kalint.main(args) == 1
    assert _json.loads(out.read_text())["count"] == 3


# --- KA024-KA027: the determinism-taint layer (ISSUE 17) ---------------------

DETERMINISM = FIXTURES / "determinism"


def test_determinism_fixture_findings_exact():
    """The whole fixture mini-package, pinned exactly: every seeded
    source->sink flow flags ONCE at the source, and every clean variant
    (sorted producer, declared ts field, monotonic clock, snapshot under
    the writers' lock, .sort() before the dump) stays silent."""
    findings = kalint.lint_tree(DETERMINISM)
    keys = sorted((f.rule, f.path, f.line) for f in findings)
    assert keys == [
        ("KA024", "determinism/edges.py", 15),
        ("KA024", "determinism/edges.py", 22),
        ("KA024", "determinism/edges.py", 28),
        ("KA024", "determinism/emit.py", 13),
        ("KA024", "determinism/emit.py", 24),
        ("KA025", "determinism/clock.py", 14),
        ("KA025", "determinism/clock.py", 26),
        ("KA026", "determinism/fsenum.py", 12),
        ("KA026", "determinism/fsenum.py", 23),
        ("KA027", "determinism/daemon/supervisor.py", 29),
    ]
    # every determinism finding carries its source->sink chain (SARIF
    # codeFlows and --explain both feed off it)
    assert all(f.chain for f in findings)


def test_ka024_cross_function_chain_names_the_sink_hop():
    # the PR 15/16 bug shape: a helper whose RETURN VALUE the caller
    # serializes — the chain must cross the function boundary
    findings = [f for f in kalint.lint_tree(DETERMINISM)
                if f.rule == "KA024" and f.path.endswith("emit.py")]
    (direct, via_helper) = sorted(findings, key=lambda f: f.line)
    assert direct.chain == ("emit.py::report@14",)
    assert via_helper.chain == (
        "emit.py::_payload@30", "emit.py::envelope@31")
    assert "PYTHONHASHSEED-dependent" in via_helper.message
    assert "json.dumps serialization at emit.py::envelope" \
        in via_helper.message


def test_ka025_names_the_allowlist_and_the_sink():
    ka025 = [f for f in kalint.lint_tree(DETERMINISM)
             if f.rule == "KA025"]
    wall, uid = sorted(ka025, key=lambda f: f.line)
    assert "wall-clock read time.time()" in wall.message
    assert "declared timestamp/identity field" in wall.message
    assert "*timestamp*" in wall.message      # the allowlist is printed
    assert "uuid.uuid4() draw" in uid.message


def test_ka026_names_the_enumeration_order():
    ka026 = [f for f in kalint.lint_tree(DETERMINISM)
             if f.rule == "KA026"]
    assert len(ka026) == 2
    for f in ka026:
        assert "filesystem enumeration order (OS-dependent)" in f.message
        assert "sorted(" in f.message


def test_ka027_names_the_racing_writer_thread():
    (ka027,) = [f for f in kalint.lint_tree(DETERMINISM)
                if f.rule == "KA027"]
    assert "ClusterSupervisor.samples" in ka027.message
    assert ".items() view drain" in ka027.message
    assert "byte-pinned sink" in ka027.message


def test_determinism_sanitizer_edge_cases_all_flag():
    """The satellite-4 traps: sorted() on the WRONG axis discharges
    nothing, a re-shuffle after a sort re-taints, and list(S) merely
    freezes the arbitrary order — while .sort() on the materialized
    list IS a discharge (materialize_clean stays silent)."""
    edges = [f for f in kalint.lint_tree(DETERMINISM)
             if f.path.endswith("edges.py")]
    assert [(f.rule, f.line) for f in sorted(edges, key=lambda f: f.line)] \
        == [("KA024", 15), ("KA024", 22), ("KA024", 28)]
    assert any("re-shuffled sequence order" in f.message for f in edges)


def test_determinism_repo_sweep_is_clean():
    # The ISSUE 17 triage landed: the two real findings (controller
    # ledger timestamp outside a declared field, unsorted os.listdir in
    # two smoke journald scans) were FIXED; the benign flows (pruning
    # horizon compared-not-serialized, commutative set-difference count
    # loops, id() memo keys through a local) are reason-suppressed at
    # their sites with the source->sink chain cited.
    findings = kalint.lint_package(use_cache=False)
    assert not [f for f in findings
                if f.rule in ("KA024", "KA025", "KA026", "KA027")]


def test_determinism_rules_are_documented():
    for rule in ("KA024", "KA025", "KA026", "KA027", "KA028"):
        assert rule in kalint.RULES and rule in kalint.RULE_DOCS


# --- KA028: deadline cross-pricing of the controller act path ----------------

ACT_TREE = {
    "__init__.py": "",
    "daemon/__init__.py": "",
    "daemon/controller.py": (
        "class RebalanceController:\n"
        "    def _act(self, verdict):\n"
        "        return self.sup.controller_execute(verdict)\n"
    ),
    "daemon/supervisor.py": (
        "def poll(env_float):\n"
        '    return env_float("KA_EXEC_POLL_TIMEOUT")\n\n\n'
        "class Sup:\n"
        "    def controller_execute(self, verdict, env_float=None):\n"
        "        return poll(env_float)\n"
    ),
}


def test_ka028_bridges_the_untyped_supervisor_seam(tmp_path):
    # `self.sup` is untyped, so the resolver drops the _act ->
    # controller_execute edge; the name-based bridge must restore it and
    # price the executor poll budget against the move window.
    root = _write_tree(tmp_path, ACT_TREE)
    project = kalint.build_project(root)
    flagged = kalint.check_act_budget(project, {}, budget=100.0)
    assert [f.rule for f in flagged] == ["KA028"]
    (f,) = flagged
    assert f.path.endswith("daemon/supervisor.py")
    assert "KA_EXEC_POLL_TIMEOUT" in f.message
    assert kalint.ACT_BUDGET_KNOB in f.message
    hops = [hop.partition("@")[0] for hop in f.chain]
    assert hops == [
        "daemon/controller.py::RebalanceController._act",
        "daemon/supervisor.py::Sup.controller_execute",
        "daemon/supervisor.py::poll",
    ]


def test_ka028_window_knob_is_the_dial(tmp_path):
    root = _write_tree(tmp_path, ACT_TREE)
    project = kalint.build_project(root)
    # executor envelope blown past the default 3600 s window: flagged
    flagged = kalint.check_act_budget(project, {}, {
        "KA_EXEC_POLL_TIMEOUT": 7200.0,
    })
    assert [f.rule for f in flagged] == ["KA028"]
    # a wider declared window absorbs the same envelope
    assert kalint.check_act_budget(project, {}, {
        "KA_EXEC_POLL_TIMEOUT": 7200.0,
        kalint.ACT_BUDGET_KNOB: 10000.0,
    }) == []


def test_ka028_default_envelope_fits_the_default_window(tmp_path):
    # the shipped defaults must be coherent: 600 s of executor poll
    # inside a 3600 s move window — the fixture is CLEAN end to end
    root = _write_tree(tmp_path, ACT_TREE)
    assert "KA028" not in rules_of(kalint.lint_tree(root))


def test_ka028_repo_sweep_is_clean():
    # the REAL act path (controller._act -> supervisor.controller_execute
    # -> executor convergence poll) prices under the shipped window
    findings = kalint.lint_package(use_cache=False)
    assert not [f for f in findings if f.rule == "KA028"]


def test_ka028_fires_on_the_real_act_path_at_a_tight_budget():
    # and the same sweep DOES see the bridged chain when the window
    # shrinks below the executor envelope — the rule is not vacuous
    from kafka_assigner_tpu.analysis.kalint.driver import _smoke_scripts

    repo = _Path(__file__).resolve().parent.parent
    project = kalint.build_project(
        repo / "kafka_assigner_tpu",
        extra_modules=_smoke_scripts(repo))
    flagged = kalint.check_act_budget(project, {}, budget=100.0)
    assert flagged, "tight budget must flag the real act path"
    chain_text = " -> ".join(flagged[0].chain)
    assert "daemon/controller.py::RebalanceController._act" in chain_text
    assert "controller_execute" in chain_text
    assert "exec/engine.py" in chain_text


# --- KA030: the fleet-ledger bulkhead (ISSUE 20) ------------------------------

KA030_SNIPPET = (
    "import json, os\n"
    "\n"
    "def peek(jdir):\n"
    '    with open(os.path.join(jdir, "ka-fleet.json")) as f:\n'
    "        return json.load(f)\n"
)


def test_ka030_trips_outside_the_fleet_module():
    findings = kalint.lint_source(KA030_SNIPPET, "daemon/service.py")
    assert any(f.rule == "KA030" and f.line == 4 for f in findings)


def test_ka030_silent_inside_the_fleet_bulkhead():
    assert "KA030" not in rules_of(
        kalint.lint_source(KA030_SNIPPET, "daemon/fleet.py")
    )


def test_ka030_trips_anywhere_in_the_package():
    # the bulkhead is package-wide, not just daemon/: a CLI helper
    # spelling the ledger name is just as able to tear it
    findings = kalint.lint_source(KA030_SNIPPET, "utils/debugtool.py")
    assert "KA030" in rules_of(findings)


def test_ka030_exempts_docstring_prose():
    src = (
        '"""Module prose may explain the ka-fleet.json ledger."""\n'
        "\n"
        "def helper():\n"
        '    """Reads go through FleetScheduler, never ka-fleet.json."""\n'
        "    return None\n"
    )
    assert "KA030" not in rules_of(
        kalint.lint_source(src, "daemon/service.py")
    )


def test_ka030_suppressible_with_a_reason():
    src = (
        "import os\n"
        'LEDGER = "ka-fleet.json"  '
        "# kalint: disable=KA030 -- migration shim reads the old location\n"
    )
    assert "KA030" not in rules_of(
        kalint.lint_source(src, "daemon/service.py")
    )


def test_ka030_repo_sweep_is_clean():
    findings = kalint.lint_package(use_cache=False)
    assert not [f for f in findings if f.rule == "KA030"]
