"""The in-repo ZooKeeper jute test server, shared by the socket tests, the
golden-frame pins, ``scripts/bench_zk_ingest.py``, and the chaos soak
(``scripts/chaos_soak.py``).

A minimal single-purpose server speaking the actual ZooKeeper wire protocol
over a real TCP port: session handshake plus the read subset (getChildren /
getData / exists / ping / closeSession) over a static znode tree.
"""
from __future__ import annotations

import json
import queue
import socket
import struct
import threading
import time


class JuteZkServer(threading.Thread):
    """Serves a static znode tree over the real wire protocol. ``tree`` maps
    full znode path -> bytes (data); directories are implied by children
    paths.

    ``reply_delay_s`` injects one-way latency: every reply is released
    ``reply_delay_s`` after its request was processed, by a per-connection
    sender thread that preserves reply order — so pipelined requests see
    their delays overlap (network latency), while a serial client pays the
    delay per round-trip. ``scripts/bench_zk_ingest.py`` uses this to
    measure the serial-vs-pipelined ingest gap hermetically. ``port``
    pins the listen port (0 = ephemeral) so restart/retry tests can bring a
    server up on an address a client is already retrying.

    ``expire_handshakes``: the first N connections receive the
    session-expired ConnectResponse (negotiated timeOut=0, sessionId=0 —
    what a real server sends when the client presents a dead session) and
    are then closed; connection N+1 onward handshakes normally. Exercises
    the client's ``"session expired during handshake"`` branch end-to-end.
    """

    def __init__(self, tree, reply_delay_s=0.0, port=0, expire_handshakes=0):
        super().__init__(daemon=True)
        self.tree = dict(tree)
        self.reply_delay_s = reply_delay_s
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", port))
        self.sock.listen(64)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self._expire_lock = threading.Lock()
        self._expire_remaining = int(expire_handshakes)
        # Children index, built once: the per-request O(tree) prefix scan
        # dominated the pipelined bench (~0.4 ms/op of pure fixture cost)
        # and hid the transport latency this server exists to model.
        self._kids = {}
        for p in self.tree:
            parent = ""
            for seg in p.strip("/").split("/"):
                self._kids.setdefault(parent + "/", set()).add(seg)
                parent = f"{parent}/{seg}"

    # -- jute helpers -----------------------------------------------------

    @staticmethod
    def _buf(data):
        return struct.pack(">i", len(data)) + data

    @staticmethod
    def _stat(data_len, n_children):
        return struct.pack(
            ">qqqqiiiqiiq", 1, 1, 0, 0, 0, 0, 0, 0, data_len, n_children, 1
        )

    def _children(self, path):
        return sorted(self._kids.get(path.rstrip("/") + "/", ()))

    def _exists(self, path):
        return path in self.tree or bool(self._children(path))

    # -- server loop ------------------------------------------------------

    def run(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            # Mirror real ZooKeeper: replies must not sit in Nagle's buffer
            # waiting for a delayed ACK while the client pipelines.
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn):
        # Delayed-reply mode: replies queue to a per-connection sender that
        # releases each one reply_delay_s after processing, in order — the
        # reader keeps consuming pipelined requests meanwhile, so concurrent
        # requests overlap their latency exactly like a real network RTT.
        sender_q = sender = None
        if self.reply_delay_s:
            sender_q = queue.Queue()

            def _sender():
                while True:
                    item = sender_q.get()
                    if item is None:
                        return
                    due, payload = item
                    delay = due - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
                    try:
                        conn.sendall(struct.pack(">i", len(payload)) + payload)
                    except OSError:
                        return

            sender = threading.Thread(target=_sender, daemon=True)
            sender.start()

        def send(payload):
            if sender_q is None:
                self._send_frame(conn, payload)
            else:
                sender_q.put(
                    (time.monotonic() + self.reply_delay_s, payload)
                )

        try:
            frame = self._recv_frame(conn)
            if frame is None:
                return
            # ConnectRequest: proto, lastZxid, timeOut, sessionId, passwd
            # [+ readOnly byte for 3.4+ clients].
            _, _, timeout_ms, _ = struct.unpack(">iqiq", frame[:24])
            has_ro = len(frame) > 24 + 4 + 16
            with self._expire_lock:
                expire = self._expire_remaining > 0
                if expire:
                    self._expire_remaining -= 1
            if expire:
                # Session-expired ConnectResponse: negotiated timeout 0,
                # session id 0, then close — the real server's behavior.
                send(
                    struct.pack(">iiq", 0, 0, 0)
                    + self._buf(b"\x00" * 16)
                    + (b"\x00" if has_ro else b"")
                )
                return
            resp = (
                struct.pack(">iiq", 0, timeout_ms, 0x1EAF)
                + self._buf(b"\x00" * 16)
                + (b"\x00" if has_ro else b"")
            )
            send(resp)
            while True:
                frame = self._recv_frame(conn)
                if frame is None:
                    return
                xid, op = struct.unpack(">ii", frame[:8])
                body = frame[8:]
                if op == 11:  # ping
                    send(struct.pack(">iqi", -2, 1, 0))
                    continue
                if op == -11:  # closeSession
                    send(struct.pack(">iqi", xid, 1, 0))
                    return
                (plen,) = struct.unpack(">i", body[:4])
                path = body[4:4 + plen].decode("utf-8")
                if op == 8:  # getChildren
                    kids = self._children(path)
                    if not self._exists(path):
                        send(struct.pack(">iqi", xid, 1, -101))
                        continue
                    payload = struct.pack(">iqi", xid, 1, 0)
                    payload += struct.pack(">i", len(kids))
                    for k in kids:
                        payload += self._buf(k.encode("utf-8"))
                    send(payload)
                elif op == 4:  # getData
                    data = self.tree.get(path)
                    if data is None:
                        send(struct.pack(">iqi", xid, 1, -101))
                        continue
                    payload = (
                        struct.pack(">iqi", xid, 1, 0)
                        + self._buf(data)
                        + self._stat(len(data), len(self._children(path)))
                    )
                    send(payload)
                elif op == 3:  # exists
                    if self._exists(path):
                        payload = struct.pack(">iqi", xid, 1, 0) + self._stat(
                            len(self.tree.get(path, b"")),
                            len(self._children(path)),
                        )
                    else:
                        payload = struct.pack(">iqi", xid, 1, -101)
                    send(payload)
                else:  # unimplemented op: loud error, not a hang
                    send(struct.pack(">iqi", xid, 1, -6))
        except (OSError, struct.error):
            pass
        finally:
            if sender_q is not None:
                # FIFO drain: queued replies flush before the close.
                sender_q.put(None)
                sender.join(timeout=10)
            conn.close()

    @staticmethod
    def _recv_frame(conn):
        header = b""
        while len(header) < 4:
            chunk = conn.recv(4 - len(header))
            if not chunk:
                return None
            header += chunk
        (n,) = struct.unpack(">i", header)
        data = b""
        while len(data) < n:
            chunk = conn.recv(n - len(data))
            if not chunk:
                return None
            data += chunk
        return data

    @staticmethod
    def _send_frame(conn, payload):
        conn.sendall(struct.pack(">i", len(payload)) + payload)

    def shutdown(self):
        self._stop.set()
        self.sock.close()


def cluster_tree():
    """The standard four-broker / two-topic fixture tree shared by the
    socket tests and the chaos soak."""
    brokers = {
        "1": {"host": "h1", "port": 9092, "rack": "ra"},
        "2": {"host": None, "endpoints": ["PLAINTEXT://h2:9093"], "rack": "rb"},
        "3": {"host": "h3", "port": 9092, "rack": "rc"},
        "4": {"host": "h4", "port": 9092, "rack": "ra"},
    }
    topics = {
        "events": {"partitions": {"0": [1, 2, 3], "1": [2, 3, 4]}},
        "logs": {"partitions": {"0": [3, 4]}},
    }
    tree = {}
    for bid, meta in brokers.items():
        tree[f"/brokers/ids/{bid}"] = json.dumps(meta).encode()
    for t, meta in topics.items():
        tree[f"/brokers/topics/{t}"] = json.dumps(meta).encode()
    return tree
