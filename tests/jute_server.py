"""The in-repo ZooKeeper jute test server, shared by the socket tests, the
golden-frame pins, ``scripts/bench_zk_ingest.py``, and the chaos soak
(``scripts/chaos_soak.py``).

A minimal single-purpose server speaking the actual ZooKeeper wire protocol
over a real TCP port: session handshake plus the read subset (getChildren /
getData / exists / ping / closeSession) over a znode tree — and, for the
plan execution engine (ISSUE 7), the write subset (create / setData /
delete) that MUTATES the tree, plus a simulated Kafka controller: when
``/admin/reassign_partitions`` is created, the server applies the described
replica moves to the topic znodes after ``controller_delay_ops`` further
requests and deletes the admin znode — the deterministic hermetic stand-in
for the controller's asynchronous reassignment execution.

Watches (ISSUE 8, the resident daemon's churn feed): one-shot data/child
watches registered by the watch flag on getData/getChildren, fired as
WatcherEvent frames (xid -1) on create/setData/delete AND on the simulated
controller's own applies, exactly like a real server.
"""
from __future__ import annotations

import json
import queue
import socket
import struct
import threading
import time


class JuteZkServer(threading.Thread):
    """Serves a static znode tree over the real wire protocol. ``tree`` maps
    full znode path -> bytes (data); directories are implied by children
    paths.

    ``reply_delay_s`` injects one-way latency: every reply is released
    ``reply_delay_s`` after its request was processed, by a per-connection
    sender thread that preserves reply order — so pipelined requests see
    their delays overlap (network latency), while a serial client pays the
    delay per round-trip. ``scripts/bench_zk_ingest.py`` uses this to
    measure the serial-vs-pipelined ingest gap hermetically. ``port``
    pins the listen port (0 = ephemeral) so restart/retry tests can bring a
    server up on an address a client is already retrying.

    ``expire_handshakes``: the first N connections receive the
    session-expired ConnectResponse (negotiated timeOut=0, sessionId=0 —
    what a real server sends when the client presents a dead session) and
    are then closed; connection N+1 onward handshakes normally. Exercises
    the client's ``"session expired during handshake"`` branch end-to-end.
    """

    def __init__(self, tree, reply_delay_s=0.0, port=0, expire_handshakes=0,
                 controller_delay_ops=2, writes_enabled=True):
        super().__init__(daemon=True)
        self.tree = dict(tree)
        self.reply_delay_s = reply_delay_s
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", port))
        self.sock.listen(64)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self._expire_lock = threading.Lock()
        self._expire_remaining = int(expire_handshakes)
        # Write/controller state: one lock guards tree + children-index
        # mutation (writes arrive on per-connection threads) and the
        # pending simulated-controller reassignment.
        self._tree_lock = threading.Lock()
        self.writes_enabled = writes_enabled
        self.controller_delay_ops = int(controller_delay_ops)
        self._pending_reassign = None   # (plan dict, remaining op count)
        self.write_ops = {"create": 0, "setData": 0, "delete": 0}
        # Children index, built once: the per-request O(tree) prefix scan
        # dominated the pipelined bench (~0.4 ms/op of pure fixture cost)
        # and hid the transport latency this server exists to model.
        self._kids = {}
        for p in self.tree:
            self._index_path(p)
        # Live accepted connections, closed by shutdown(): a quorum
        # blackout (the breaker chaos rows, ISSUE 9) must kill ESTABLISHED
        # sessions too, not just refuse new ones.
        self._conns = set()
        self._conns_lock = threading.Lock()
        # Watch registries (ISSUE 8): one-shot, like real ZooKeeper — a
        # getData/getChildren request with the watch flag set registers its
        # connection's send fn; a mutation (client write OR the simulated
        # controller's apply) fires-and-forgets a WatcherEvent frame and
        # removes the registration.
        self._watch_lock = threading.Lock()
        self._data_watches = {}   # path -> [send fn, ...]
        self._child_watches = {}  # path -> [send fn, ...]

    def _index_path(self, p):
        parent = ""
        for seg in p.strip("/").split("/"):
            self._kids.setdefault(parent + "/", set()).add(seg)
            parent = f"{parent}/{seg}"

    def _unindex_path(self, p):
        parent, _, name = p.rpartition("/")
        kids = self._kids.get(parent + "/")
        if kids is not None:
            kids.discard(name)

    # -- jute helpers -----------------------------------------------------

    @staticmethod
    def _buf(data):
        return struct.pack(">i", len(data)) + data

    @staticmethod
    def _stat(data_len, n_children):
        return struct.pack(
            ">qqqqiiiqiiq", 1, 1, 0, 0, 0, 0, 0, 0, data_len, n_children, 1
        )

    def _children(self, path):
        return sorted(self._kids.get(path.rstrip("/") + "/", ()))

    def _exists(self, path):
        return path in self.tree or bool(self._children(path))

    # -- watches (one-shot, like real ZooKeeper) ---------------------------

    def _register_watch(self, table, path, send):
        with self._watch_lock:
            table.setdefault(path, []).append(send)

    def _fire_watches(self, table, path, ev_type):
        """Send a WatcherEvent (xid -1, zxid -1) to every one-shot watcher
        of ``path`` in ``table`` and drop the registrations. Dead
        connections are skipped — real servers fire and forget too."""
        with self._watch_lock:
            senders = table.pop(path, [])
        if not senders:
            return
        frame = (
            struct.pack(">iqi", -1, -1, 0)          # xid, zxid, err
            + struct.pack(">ii", ev_type, 3)         # type, SyncConnected
            + self._buf(path.encode("utf-8"))
        )
        for send in senders:
            try:
                send(frame)
            except OSError:
                continue  # watcher's connection is gone; nothing to notify

    def _fire_mutation(self, path, ev_type):
        """The watch fan-out for one znode mutation: the node's DATA watch
        with the given type, plus the parent's CHILD watch when the child
        set changed (create/delete)."""
        self._fire_watches(self._data_watches, path, ev_type)
        if ev_type in (1, 2):  # NodeCreated / NodeDeleted
            parent = path.rpartition("/")[0]
            if parent:
                self._fire_watches(self._child_watches, parent, 4)

    # -- simulated Kafka controller ---------------------------------------

    def _accept_reassignment(self, data):
        """Record a freshly-created ``/admin/reassign_partitions`` payload;
        the moves apply after ``controller_delay_ops`` further requests
        (deterministic asynchrony — a client that polls sees the admin
        znode present and the old assignment first, like a real cluster).
        Caller holds the tree lock."""
        try:
            plan = json.loads(data)
        except ValueError:
            return  # a real controller logs and ignores garbage
        self._pending_reassign = (plan, self.controller_delay_ops)

    def _controller_tick(self):
        """Advance the simulated controller by one observed request; at
        zero, apply the pending moves to the topic (and state) znodes and
        delete the admin znode — the controller's completion signal. The
        mutations fire watches like any client write would (the daemon's
        churn feed sees controller-applied reassignments, ISSUE 8)."""
        fired = []
        with self._tree_lock:
            if self._pending_reassign is None:
                return
            plan, remaining = self._pending_reassign
            if remaining > 0:
                self._pending_reassign = (plan, remaining - 1)
                return
            self._pending_reassign = None
            for entry in plan.get("partitions", []):
                t, p = entry["topic"], int(entry["partition"])
                replicas = [int(r) for r in entry["replicas"]]
                tpath = f"/brokers/topics/{t}"
                if tpath in self.tree:
                    meta = json.loads(self.tree[tpath])
                    meta.setdefault("partitions", {})[str(p)] = replicas
                    self.tree[tpath] = json.dumps(meta).encode()
                    fired.append((tpath, 3))
                spath = f"{tpath}/partitions/{p}/state"
                if spath in self.tree:
                    smeta = json.loads(self.tree[spath])
                    smeta["isr"] = replicas
                    smeta["leader"] = replicas[0] if replicas else -1
                    self.tree[spath] = json.dumps(smeta).encode()
                    fired.append((spath, 3))
            admin = "/admin/reassign_partitions"
            if admin in self.tree:
                del self.tree[admin]
                self._unindex_path(admin)
                fired.append((admin, 2))
        for path, ev_type in dict.fromkeys(fired):
            self._fire_mutation(path, ev_type)

    # -- server loop ------------------------------------------------------

    def run(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            # Mirror real ZooKeeper: replies must not sit in Nagle's buffer
            # waiting for a delayed ACK while the client pipelines.
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn):
        # Delayed-reply mode: replies queue to a per-connection sender that
        # releases each one reply_delay_s after processing, in order — the
        # reader keeps consuming pipelined requests meanwhile, so concurrent
        # requests overlap their latency exactly like a real network RTT.
        sender_q = sender = None
        if self.reply_delay_s:
            sender_q = queue.Queue()

            def _sender():
                while True:
                    item = sender_q.get()
                    if item is None:
                        return
                    due, payload = item
                    delay = due - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
                    try:
                        conn.sendall(struct.pack(">i", len(payload)) + payload)
                    except OSError:
                        return

            sender = threading.Thread(target=_sender, daemon=True)
            sender.start()

        # One lock per connection: watch notifications arrive from OTHER
        # connections' threads, and two un-serialized sendall calls could
        # interleave partial frames.
        send_lock = threading.Lock()

        def send(payload):
            if sender_q is None:
                with send_lock:
                    self._send_frame(conn, payload)
            else:
                sender_q.put(
                    (time.monotonic() + self.reply_delay_s, payload)
                )

        try:
            frame = self._recv_frame(conn)
            if frame is None:
                return
            # ConnectRequest: proto, lastZxid, timeOut, sessionId, passwd
            # [+ readOnly byte for 3.4+ clients].
            _, _, timeout_ms, _ = struct.unpack(">iqiq", frame[:24])
            has_ro = len(frame) > 24 + 4 + 16
            with self._expire_lock:
                expire = self._expire_remaining > 0
                if expire:
                    self._expire_remaining -= 1
            if expire:
                # Session-expired ConnectResponse: negotiated timeout 0,
                # session id 0, then close — the real server's behavior.
                send(
                    struct.pack(">iiq", 0, 0, 0)
                    + self._buf(b"\x00" * 16)
                    + (b"\x00" if has_ro else b"")
                )
                return
            resp = (
                struct.pack(">iiq", 0, timeout_ms, 0x1EAF)
                + self._buf(b"\x00" * 16)
                + (b"\x00" if has_ro else b"")
            )
            send(resp)
            while True:
                frame = self._recv_frame(conn)
                if frame is None:
                    return
                xid, op = struct.unpack(">ii", frame[:8])
                body = frame[8:]
                if op == 11:  # ping
                    send(struct.pack(">iqi", -2, 1, 0))
                    continue
                if op == -11:  # closeSession
                    send(struct.pack(">iqi", xid, 1, 0))
                    return
                self._controller_tick()
                (plen,) = struct.unpack(">i", body[:4])
                path = body[4:4 + plen].decode("utf-8")
                if op == 1 and self.writes_enabled:  # create
                    (dlen,) = struct.unpack(">i", body[4 + plen:8 + plen])
                    data = body[8 + plen:8 + plen + max(0, dlen)]
                    with self._tree_lock:
                        if path in self.tree:
                            send(struct.pack(">iqi", xid, 1, -110))
                            continue
                        parent = path.rpartition("/")[0]
                        if parent and not self._exists(parent):
                            # real ZK: creating under a missing parent is
                            # NoNode — clients must makepath explicitly
                            send(struct.pack(">iqi", xid, 1, -101))
                            continue
                        self.write_ops["create"] += 1
                        self.tree[path] = data
                        self._index_path(path)
                        if path == "/admin/reassign_partitions":
                            self._accept_reassignment(data)
                    payload = struct.pack(">iqi", xid, 1, 0) + self._buf(
                        path.encode("utf-8")
                    )
                    send(payload)
                    self._fire_mutation(path, 1)  # NodeCreated
                elif op == 5 and self.writes_enabled:  # setData
                    (dlen,) = struct.unpack(">i", body[4 + plen:8 + plen])
                    data = body[8 + plen:8 + plen + max(0, dlen)]
                    with self._tree_lock:
                        if path not in self.tree:
                            send(struct.pack(">iqi", xid, 1, -101))
                            continue
                        self.write_ops["setData"] += 1
                        self.tree[path] = data
                    payload = struct.pack(">iqi", xid, 1, 0) + self._stat(
                        len(data), len(self._children(path))
                    )
                    send(payload)
                    self._fire_mutation(path, 3)  # NodeDataChanged
                elif op == 2 and self.writes_enabled:  # delete
                    with self._tree_lock:
                        if path not in self.tree:
                            send(struct.pack(">iqi", xid, 1, -101))
                            continue
                        self.write_ops["delete"] += 1
                        del self.tree[path]
                        self._unindex_path(path)
                    send(struct.pack(">iqi", xid, 1, 0))
                    self._fire_mutation(path, 2)  # NodeDeleted
                elif op == 8:  # getChildren
                    kids = self._children(path)
                    if not self._exists(path):
                        send(struct.pack(">iqi", xid, 1, -101))
                        continue
                    if len(body) > 4 + plen and body[4 + plen]:
                        self._register_watch(self._child_watches, path, send)
                    payload = struct.pack(">iqi", xid, 1, 0)
                    payload += struct.pack(">i", len(kids))
                    for k in kids:
                        payload += self._buf(k.encode("utf-8"))
                    send(payload)
                elif op == 4:  # getData
                    data = self.tree.get(path)
                    if data is None:
                        send(struct.pack(">iqi", xid, 1, -101))
                        continue
                    if len(body) > 4 + plen and body[4 + plen]:
                        self._register_watch(self._data_watches, path, send)
                    payload = (
                        struct.pack(">iqi", xid, 1, 0)
                        + self._buf(data)
                        + self._stat(len(data), len(self._children(path)))
                    )
                    send(payload)
                elif op == 3:  # exists
                    if self._exists(path):
                        payload = struct.pack(">iqi", xid, 1, 0) + self._stat(
                            len(self.tree.get(path, b"")),
                            len(self._children(path)),
                        )
                    else:
                        payload = struct.pack(">iqi", xid, 1, -101)
                    send(payload)
                else:  # unimplemented op: loud error, not a hang
                    send(struct.pack(">iqi", xid, 1, -6))
        except (OSError, struct.error):
            pass
        finally:
            if sender_q is not None:
                # FIFO drain: queued replies flush before the close.
                sender_q.put(None)
                sender.join(timeout=10)
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)

    @staticmethod
    def _recv_frame(conn):
        header = b""
        while len(header) < 4:
            chunk = conn.recv(4 - len(header))
            if not chunk:
                return None
            header += chunk
        (n,) = struct.unpack(">i", header)
        data = b""
        while len(data) < n:
            chunk = conn.recv(n - len(data))
            if not chunk:
                return None
            data += chunk
        return data

    @staticmethod
    def _send_frame(conn, payload):
        conn.sendall(struct.pack(">i", len(payload)) + payload)

    def shutdown(self):
        self._stop.set()
        # Wake the accept loop: a thread blocked in accept() holds the
        # kernel socket alive past close(), leaving a ghost LISTEN that
        # blocks rebinding the pinned port (the breaker chaos rows restart
        # a server on the SAME port).
        try:
            poke = socket.create_connection(("127.0.0.1", self.port),
                                            timeout=1.0)
            poke.close()
        except OSError:  # accept loop already gone; nothing to wake
            pass
        self.sock.close()
        # Kill established sessions too: a stopped quorum is a BLACKOUT
        # for its clients, not a server that answers forever.
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:  # already dying on its own thread
                continue


def cluster_tree():
    """The standard four-broker / two-topic fixture tree shared by the
    socket tests and the chaos soak."""
    brokers = {
        "1": {"host": "h1", "port": 9092, "rack": "ra"},
        "2": {"host": None, "endpoints": ["PLAINTEXT://h2:9093"], "rack": "rb"},
        "3": {"host": "h3", "port": 9092, "rack": "rc"},
        "4": {"host": "h4", "port": 9092, "rack": "ra"},
    }
    topics = {
        "events": {"partitions": {"0": [1, 2, 3], "1": [2, 3, 4]}},
        "logs": {"partitions": {"0": [3, 4]}},
    }
    tree = {}
    for bid, meta in brokers.items():
        tree[f"/brokers/ids/{bid}"] = json.dumps(meta).encode()
    for t, meta in topics.items():
        tree[f"/brokers/topics/{t}"] = json.dumps(meta).encode()
    return tree


def exec_snapshot_cluster():
    """The shared SNAPSHOT-backend fixture for the write-path harnesses
    (``scripts/chaos_soak.py`` exec matrix, ``scripts/exec_smoke.py``,
    ``tests/test_exec.py``): 9 brokers over 3 racks, so draining one broker
    always leaves every rack with capacity — the greedy plan is feasible
    and deterministic, and it spans multiple waves at the harness wave
    size. One copy, so the matrix and the smoke can never drift apart."""
    return {
        "brokers": [
            {"id": i, "host": f"h{i}", "port": 9092,
             "rack": f"r{(i - 1) % 3}"}
            for i in range(1, 10)
        ],
        "topics": {
            "events": {
                str(p): [1 + (p + r * 3) % 9 for r in range(3)]
                for p in range(6)
            },
            "logs": {
                str(p): [1 + (p + r * 3) % 9 for r in range(2)]
                for p in range(4)
            },
        },
    }


def cluster_tree_with_states():
    """The fixture tree plus the modern per-partition ``state`` znodes
    (``/brokers/topics/<t>/partitions/<p>/state`` carrying leader+ISR) —
    what the execution engine's convergence poll reads on clusters that
    have them; the plain ``cluster_tree`` covers the fallback layout."""
    tree = cluster_tree()
    for path in [p for p in tree if p.startswith("/brokers/topics/")]:
        meta = json.loads(tree[path])
        for p, reps in meta.get("partitions", {}).items():
            tree[f"{path}/partitions/{p}/state"] = json.dumps(
                {"isr": list(reps), "leader": reps[0] if reps else -1}
            ).encode()
    return tree
