"""BASELINE config 5 pinned as a test (VERDICT round 1 #6): 256 candidate
single-broker removals over a 1k-broker cluster, sharded across the 8-device
virtual mesh — the fleet-scale what-if throughput scenario the reference can
only answer one process run at a time.
"""
from __future__ import annotations

import os
import time

import jax
import pytest

from kafka_assigner_tpu.models.synthetic import build_config5
from kafka_assigner_tpu.parallel.mesh import build_mesh
from kafka_assigner_tpu.parallel.whatif import evaluate_removal_scenarios


@pytest.mark.slow
def test_config5_256_scenarios_on_8dev_mesh():
    topics, live, rack_map = build_config5()
    assert len(jax.devices()) == 8, "conftest should provide 8 virtual devices"
    mesh = build_mesh()  # 8x1: scenarios across all devices
    scenarios = [[b] for b in range(256)]

    t0 = time.perf_counter()
    results = evaluate_removal_scenarios(
        topics, live, rack_map, scenarios, 3, mesh=mesh
    )
    cold_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    results = evaluate_removal_scenarios(
        topics, live, rack_map, scenarios, 3, mesh=mesh
    )
    warm_s = time.perf_counter() - t0

    assert len(results) == 256
    assert all(r.feasible for r in results), [
        r.removed for r in results if not r.feasible
    ][:5]
    # Every scenario moves at least the replicas the removed broker held and
    # no more than a small multiple (ripple from capacity re-balancing).
    held = {b: 0 for b in live}
    for cur in topics.values():
        for replicas in cur.values():
            for b in replicas:
                held[b] += 1
    for r in results:
        b = r.removed[0]
        assert r.moved_replicas >= held[b], (b, r.moved_replicas, held[b])
        assert r.moved_replicas <= 3 * max(held[b], 1), (b, r.moved_replicas)
    # Per-scenario budget: 6.2 ms/scenario measured round 2 (BENCH_r02.json
    # config5_ms_per_scenario); 40 ms (~10 s for the 256-scenario sweep) keeps
    # ~6x headroom for a loaded shared box yet still fails on any 2x
    # algorithmic regression, unlike the round-1 placeholder bound of 120 s.
    # Hard-gating wall-clock on a shared box makes functional CI flake under
    # co-tenancy (ADVICE r3), so the assert is opt-in: KA_PERF_ASSERT=1 turns
    # the measurement into a pass/fail perf gate; default runs just report.
    if os.environ.get("KA_PERF_ASSERT") == "1":
        assert warm_s / 256 < 0.040, (
            f"config-5 per-scenario budget blown: {warm_s / 256 * 1000:.1f} "
            f"ms ({warm_s:.1f}s warm for 256 scenarios)"
        )
    elif warm_s / 256 >= 0.040:
        print(
            f"\nWARNING config-5 per-scenario budget exceeded (not fatal "
            f"without KA_PERF_ASSERT=1): {warm_s / 256 * 1000:.1f} ms"
        )
    print(
        f"\nconfig5: 256 scenarios cold={cold_s:.1f}s warm={warm_s:.1f}s "
        f"({warm_s / 256 * 1000:.0f} ms/scenario)"
    )
