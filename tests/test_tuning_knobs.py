"""The solver's compile-burden knobs must be semantics-invariant.

``KA_LEADER_CHUNK`` changes how many partitions each leadership scan step
unrolls; ``KA_WAVE_MODE`` changes which orphan-spread fallback chain gets
compiled. Both exist because compile time is a first-class cost on the
deployment target (remote compile over the chip tunnel) — neither may change
a single emitted byte on instances the default path solves.
"""
from __future__ import annotations

import numpy as np
import pytest

from kafka_assigner_tpu.assigner import TopicAssigner

from .test_invariants import make_cluster


def _jnp():
    import jax.numpy as jnp

    return jnp


@pytest.mark.parametrize("chunk", [1, 4, 7, 8, 16])
def test_leadership_chunk_invariant(chunk):
    """leadership_order output is identical for every chunk size, including
    chunks that do not divide P (fallback to 1)."""
    import jax

    from kafka_assigner_tpu.ops.assignment import leadership_order

    jnp = _jnp()
    rng = np.random.default_rng(7)
    p, n, rf = 64, 32, 3
    acc = np.stack([rng.choice(n, rf, replace=False) for _ in range(p)]).astype(
        np.int32
    )
    cnt = np.full(p, rf, np.int32)
    counters = rng.integers(0, 50, (n, rf)).astype(np.int32)

    ref = jax.device_get(
        leadership_order(
            jnp.asarray(acc), jnp.asarray(cnt), jnp.asarray(counters),
            jnp.int32(12345), rf,
        )
    )
    got = jax.device_get(
        leadership_order(
            jnp.asarray(acc), jnp.asarray(cnt), jnp.asarray(counters),
            jnp.int32(12345), rf, chunk,
        )
    )
    assert np.array_equal(ref[0], got[0]) and np.array_equal(ref[1], got[1])


def _solve_with_env(monkeypatch, topics, live, rack_map, **env):
    for k in ("KA_WAVE_MODE", "KA_LEADER_CHUNK"):
        monkeypatch.delenv(k, raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    return TopicAssigner("tpu").generate_assignments(topics, live, rack_map, -1)


@pytest.mark.parametrize(
    "env",
    [
        {"KA_WAVE_MODE": "fast_balance"},
        {"KA_WAVE_MODE": "fast_dense"},
        {"KA_LEADER_CHUNK": "1"},
        {"KA_LEADER_CHUNK": "4"},
        {"KA_WAVE_MODE": "not-a-mode"},
        {"KA_WAVE_MODE": "fast_balance", "KA_LEADER_CHUNK": "1"},
    ],
)
def test_solver_knobs_do_not_change_output(monkeypatch, env):
    current, live, rack_map = make_cluster(3, 16, 32, 3, 4, remove=1)
    topics = [(f"t{i}", current) for i in range(4)]
    baseline = _solve_with_env(monkeypatch, topics, live, rack_map)
    tuned = _solve_with_env(monkeypatch, topics, live, rack_map, **env)
    assert tuned == baseline


def test_ka_profile_emits_device_trace(monkeypatch, tmp_path):
    # SURVEY §5 observability: KA_PROFILE=<dir> captures a device trace
    # around the batched solve (the reference has no profiling at all).
    from kafka_assigner_tpu.assigner import TopicAssigner

    monkeypatch.setenv("KA_PROFILE", str(tmp_path))
    topics = [("t", {p: [1 + p % 8, 1 + (p + 3) % 8] for p in range(4)})]
    live = set(range(1, 17))
    racks = {b: f"r{b % 4}" for b in live}
    out = TopicAssigner("tpu").generate_assignments(topics, live, racks, -1)
    assert len(out) == 1
    traces = list(tmp_path.rglob("*.xplane.pb"))
    assert traces, f"no xplane trace under {tmp_path}"
