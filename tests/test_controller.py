"""The closed-loop rebalance controller (ISSUE 15): policy ladder, verdict
hysteresis under flap, blast-radius truncation, rolling-window persistence
across restarts, pause/resume racing an in-flight action, observe-mode
zero-writes, and the breaker-gated abort-to-rollback path — all against the
hermetic snapshot backend, with deterministic manual ``tick()`` driving
(the loop thread is parked on a huge interval)."""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

import pytest

from kafka_assigner_tpu import faults
from kafka_assigner_tpu.cli import parse_clusters_spec
from kafka_assigner_tpu.daemon import AssignerDaemon
from kafka_assigner_tpu.daemon.controller import (
    RebalanceController,
    resolve_policy,
)
from kafka_assigner_tpu.faults.inject import FaultInjector, parse_spec

from .test_daemon import req


@pytest.fixture(autouse=True)
def _fresh_injector():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(autouse=True)
def _controller_env(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_DAEMON_RESYNC_INTERVAL", "0.2")
    monkeypatch.setenv("KA_DAEMON_JOURNAL_DIR", str(tmp_path))
    # Park the loop: tests drive tick() by hand for determinism.
    monkeypatch.setenv("KA_CONTROLLER_INTERVAL", "3600")
    monkeypatch.setenv("KA_CONTROLLER_COOLDOWN", "0")
    monkeypatch.setenv("KA_CONTROLLER_CONFIRMATIONS", "2")
    monkeypatch.setenv("KA_CONTROLLER_MAX_MOVES", "32")
    monkeypatch.setenv("KA_EXEC_POLL_INTERVAL", "0.01")


def imbalanced_snapshot(tmp_path, name="cluster.json"):
    """Four brokers on four racks, every replica piled on brokers 1-2:
    the plan provably improves the composite score by more than its move
    count, so the default cost model recommends it."""
    path = tmp_path / name
    path.write_text(json.dumps({
        "brokers": [
            {"id": i, "host": f"b{i}", "port": 9092, "rack": f"r{i}"}
            for i in range(1, 5)
        ],
        "topics": {
            "hot": {str(p): [1, 2] for p in range(4)},
            "events": {"0": [1, 2, 3]},
        },
    }))
    return str(path)


def topics_of(path):
    with open(path) as f:
        return json.load(f)["topics"]


@contextlib.contextmanager
def controller_daemon(snap, **kwargs):
    kwargs.setdefault("solver", "greedy")
    d = AssignerDaemon(snap, **kwargs)
    d.start()
    try:
        yield d, d.supervisor()
    finally:
        d.shutdown()


def decisions_of(sup):
    return [e["decision"] for e in sup.controller_view()["decisions"]]


# --- policy ladder -----------------------------------------------------------

def test_off_policy_is_inert(tmp_path):
    snap = imbalanced_snapshot(tmp_path)
    with controller_daemon(snap) as (d, sup):
        assert sup.controller.policy == "off"
        assert sup.controller._thread is None  # no thread ever started
        assert sup.controller.tick() is None
        s, body, _ = req(d.http_port, "GET", "/controller")
        assert s == 200 and body["policy"] == "off"
        assert body["decisions"] == []
    assert not any(
        k.startswith("controller.") for k in d.counters()
    )


def test_resolve_policy_validates_overrides():
    assert resolve_policy("auto") == "auto"
    assert resolve_policy(None) == "off"  # the knob default
    with pytest.raises(ValueError):
        resolve_policy("yolo")


def test_clusters_spec_controller_override(tmp_path):
    snap_a = imbalanced_snapshot(tmp_path, "a.json")
    snap_b = imbalanced_snapshot(tmp_path, "b.json")
    spec = parse_clusters_spec(
        f"a={snap_a}#controller=observe;b={snap_b}"
    )
    assert spec == {"a": f"{snap_a}#controller=observe", "b": snap_b}
    d = AssignerDaemon(clusters=spec, solver="greedy")
    d.start()
    try:
        assert d.supervisors["a"].controller.policy == "observe"
        assert d.supervisors["b"].controller.policy == "off"
    finally:
        d.shutdown()
    # The JSON object form carries the same override.
    d2 = AssignerDaemon(
        clusters={"a": {"connect": snap_a, "controller": "auto"}},
        solver="greedy",
    )
    assert d2.supervisors["a"].controller.policy == "auto"
    # (never started; nothing to shut down)
    with pytest.raises(ValueError):
        AssignerDaemon(
            clusters={"a": {"connect": snap_a, "bogus": 1}},
            solver="greedy",
        )


# --- hysteresis under verdict flap ------------------------------------------

def test_verdict_flap_under_hysteresis_never_acts(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_CONTROLLER", "auto")
    snap = imbalanced_snapshot(tmp_path)
    before = topics_of(snap)
    # EVERY evaluation's verdict is flipped: recommend becomes hold.
    faults.install(FaultInjector(parse_spec(
        ";".join(f"controller:{i}=verdict-flap" for i in range(4))
    )))
    with controller_daemon(snap) as (d, sup):
        for _ in range(4):
            entry = sup.controller.tick()
            assert entry["decision"] == "hold"
            assert entry["flapped"] is True
        assert "act" not in decisions_of(sup)
        assert sup.controller_view()["streak"] == 0
    assert topics_of(snap) == before  # zero writes
    assert d.counters().get("controller.actions") is None


def test_single_flap_resets_the_streak(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_CONTROLLER", "auto")
    snap = imbalanced_snapshot(tmp_path)
    faults.install(FaultInjector(parse_spec("controller:1=verdict-flap")))
    with controller_daemon(snap) as (d, sup):
        assert sup.controller.tick()["decision"] == "confirmed"  # streak 1
        flap = sup.controller.tick()                             # flapped
        assert flap["decision"] == "hold" and flap["flapped"] is True
        assert sup.controller_view()["streak"] == 0              # reset
        assert sup.controller.tick()["decision"] == "confirmed"  # streak 1
        acted = sup.controller.tick()                            # streak 2
        assert acted["decision"] == "acted"
        assert d.counters().get("controller.actions") == 1


# --- blast radius ------------------------------------------------------------

def test_truncation_is_a_prefix_of_whole_partitions():
    plan_cur = {"t": {0: [1, 2], 1: [1, 2], 2: [1, 2]}}
    plan_new = {"t": {0: [3, 4], 1: [1, 3], 2: [3, 4]}}
    from kafka_assigner_tpu.io.json_io import format_reassignment_json

    text = (
        "CURRENT ASSIGNMENT:\n"
        + format_reassignment_json(plan_cur, topic_order=["t"])
        + "\nNEW ASSIGNMENT:\n"
        + format_reassignment_json(plan_new, topic_order=["t"])
        + "\n"
    )
    # Moves per partition: p0=2, p1=1, p2=2 (5 total). Cap 3: p0 (2) +
    # p1 (1) fit; p2 would overflow and truncation STOPS — a prefix,
    # never a skip-and-continue cherry-pick.
    out_text, moves, sha = RebalanceController._truncate(text, 3)
    assert moves == 3 and sha
    from kafka_assigner_tpu.exec.engine import parse_plan_payload

    new_sub, order = parse_plan_payload(out_text)
    cur_sub, _ = parse_plan_payload(out_text, section="current")
    assert new_sub == {"t": {0: [3, 4], 1: [1, 3]}}
    assert cur_sub == {"t": {0: [1, 2], 1: [1, 2]}}
    assert order == ["t"]
    # Cap 1: even the first partition (2 moves) overflows — nothing fits.
    _, none_moves, _ = RebalanceController._truncate(text, 1)
    assert none_moves == 0


def test_window_cap_survives_a_daemon_restart(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_CONTROLLER", "auto")
    monkeypatch.setenv("KA_CONTROLLER_CONFIRMATIONS", "1")
    monkeypatch.setenv("KA_CONTROLLER_MAX_MOVES", "3")
    # Free movement: the truncated leftover must still RECOMMEND after
    # the restart so the hold provably comes from the window, not the
    # cost model.
    monkeypatch.setenv("KA_HEALTH_MOVE_COST", "0")
    snap = imbalanced_snapshot(tmp_path)
    with controller_daemon(snap) as (d, sup):
        # The full plan is over the cap: a truncated prefix acts, and its
        # replica moves land in the persisted window ledger.
        entry = sup.controller.tick()
        assert entry["decision"] == "acted"
        assert "truncate" in decisions_of(sup)
        spent = sup.controller_view()["window"]["moves"]
        assert 0 < spent <= 3
    ledger = tmp_path / "ka-controller-default.window.json"
    assert ledger.exists()
    assert sum(n for _t, n in json.loads(
        ledger.read_text())["actions"]) == spent
    # A FRESH daemon (new process stand-in) must load the ledger: the
    # remaining imbalance still recommends, but the budget is spent —
    # the window never resets on a daemon kill. (The live MAX_MOVES knob
    # is pinned to exactly what the first daemon spent, so the hold
    # provably comes from the PERSISTED accounting.)
    monkeypatch.setenv("KA_CONTROLLER_MAX_MOVES", str(spent))
    with controller_daemon(snap) as (d2, sup2):
        assert sup2.controller_view()["window"]["moves"] == spent
        deadline = time.monotonic() + 10
        entry = None
        while time.monotonic() < deadline:
            entry = sup2.controller.tick()
            if entry["decision"] == "hold" \
                    and entry.get("reason") == "window budget spent":
                break
            time.sleep(0.1)
        assert entry["decision"] == "hold"
        assert entry["reason"] == "window budget spent"
        assert d2.counters().get("controller.actions") is None


# --- pause/resume racing an in-flight action --------------------------------

def test_pause_never_aborts_an_inflight_action(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_CONTROLLER", "auto")
    monkeypatch.setenv("KA_CONTROLLER_CONFIRMATIONS", "1")
    # Slow-ish convergence: every move needs 8 polls, so the action has
    # a window of a few seconds for the pause to race into (more sim
    # polls would snowball under the poll loop's exponential backoff).
    monkeypatch.setenv("KA_EXEC_SIM_POLLS", "8")
    monkeypatch.setenv("KA_EXEC_POLL_INTERVAL", "0.02")
    snap = imbalanced_snapshot(tmp_path)
    with controller_daemon(snap) as (d, sup):
        box = {}

        def run_tick():
            box["entry"] = sup.controller.tick()

        t = threading.Thread(target=run_tick)
        t.start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline \
                and not sup.execution_in_flight():
            time.sleep(0.01)
        assert sup.execution_in_flight(), "action never started"
        view = sup.controller.pause()  # races the in-flight action
        assert view["paused"] is True
        t.join(timeout=60)
        assert not t.is_alive()
        # The action COMPLETED despite the pause (the journal, not the
        # pause flag, owns execution safety)...
        assert box["entry"]["decision"] == "acted"
        # ...and the pause gates every LATER tick.
        assert sup.controller.tick() is None
        sup.controller.resume()
        assert sup.controller.tick() is not None
        decs = decisions_of(sup)
        assert "paused" in decs and "resumed" in decs


# --- observe mode ------------------------------------------------------------

def test_observe_mode_decides_but_never_writes(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_CONTROLLER", "observe")
    monkeypatch.setenv("KA_CONTROLLER_CONFIRMATIONS", "1")
    snap = imbalanced_snapshot(tmp_path)
    before = topics_of(snap)
    with controller_daemon(snap) as (d, sup):
        entry = sup.controller.tick()
        assert entry["decision"] == "would-act"
        assert entry["moves"] > 0
        # Observe proves the whole decision path with zero writes: the
        # snapshot is untouched, no journal was ever created, and the
        # action counters never moved.
        assert sup.controller.tick()["decision"] == "would-act"
    assert topics_of(snap) == before
    assert not [
        p for p in os.listdir(tmp_path) if p.endswith(".journal")
    ]
    counters = d.counters()
    assert counters.get("controller.evaluations", 0) >= 2
    assert counters.get("controller.actions") is None
    assert counters.get("controller.moves") is None


# --- breaker-gated abort-to-rollback ----------------------------------------

def test_exec_crash_mid_loop_rolls_back_byte_identically(
    tmp_path, monkeypatch,
):
    monkeypatch.setenv("KA_CONTROLLER", "auto")
    monkeypatch.setenv("KA_CONTROLLER_CONFIRMATIONS", "1")
    monkeypatch.setenv("KA_CONTROLLER_COOLDOWN", "600")
    monkeypatch.setenv("KA_EXEC_WAVE_SIZE", "2")
    snap = imbalanced_snapshot(tmp_path)
    before = topics_of(snap)
    # Crash at the SECOND wave boundary: wave 0 committed, real movement
    # to undo.
    faults.install(FaultInjector(parse_spec("controller:1=exec-crash")))
    with controller_daemon(snap) as (d, sup):
        entry = sup.controller.tick()
        assert entry["decision"] == "rollback" and entry["ok"] is True
        decs = decisions_of(sup)
        for expected in ("act", "abort", "rollback", "breaker-open"):
            assert expected in decs, decs
        assert sup.controller_view()["breaker"]["state"] == "open"
        # The superseded forward journal is gone; only the completed
        # rollback journal remains.
        left = [
            p for p in os.listdir(tmp_path) if p.endswith(".journal")
        ]
        assert all("rollback" in p for p in left) and left
        # While the breaker is open, ticks hold without solving (the
        # first few may hold on the post-rollback stale cache instead —
        # also a refusal-to-act, just an earlier rung of it).
        deadline = time.monotonic() + 10
        held = sup.controller.tick()
        while time.monotonic() < deadline \
                and held["reason"] == "cluster degraded":
            time.sleep(0.1)
            held = sup.controller.tick()
        assert held["decision"] == "hold"
        assert held["reason"] == "controller breaker open"
    assert topics_of(snap) == before
    counters = d.counters()
    assert counters.get("controller.rollbacks") == 1
    assert counters.get("controller.breaker_opened") == 1


def test_injected_regression_rolls_back_and_opens_breaker(
    tmp_path, monkeypatch,
):
    monkeypatch.setenv("KA_CONTROLLER", "auto")
    monkeypatch.setenv("KA_CONTROLLER_CONFIRMATIONS", "1")
    monkeypatch.setenv("KA_CONTROLLER_COOLDOWN", "600")
    snap = imbalanced_snapshot(tmp_path)
    before = topics_of(snap)
    faults.install(FaultInjector(parse_spec("controller:0=regress")))
    with controller_daemon(snap) as (d, sup):
        entry = sup.controller.tick()
        assert entry["decision"] == "rollback" and entry["ok"] is True
        abort = next(
            e for e in sup.controller_view()["decisions"]
            if e["decision"] == "abort"
        )
        assert "regression" in abort["reason"]
        assert sup.controller_view()["breaker"]["state"] == "open"
    assert topics_of(snap) == before
    assert d.counters().get("controller.regressions") == 1


# --- the /controller endpoint -----------------------------------------------

def test_controller_endpoint_get_and_pause_resume(tmp_path, monkeypatch):
    monkeypatch.setenv("KA_CONTROLLER", "observe")
    snap = imbalanced_snapshot(tmp_path)
    with controller_daemon(snap) as (d, sup):
        s, body, _ = req(d.http_port, "GET", "/controller")
        assert s == 200
        assert body["policy"] == "observe" and body["paused"] is False
        assert body["breaker"]["state"] == "closed"
        s, body, _ = req(
            d.http_port, "POST", "/controller", {"action": "pause"}
        )
        assert s == 200 and body["paused"] is True
        s, body, _ = req(
            d.http_port, "POST", "/controller", {"action": "resume"}
        )
        assert s == 200 and body["paused"] is False
        s, body, _ = req(
            d.http_port, "POST", "/controller", {"action": "explode"}
        )
        assert s == 400 and "explode" in body["error"]
        # Multi-cluster routing sanity: the per-cluster path serves too.
        s, body, _ = req(d.http_port, "GET", "/clusters/default/controller")
        assert s == 200 and body["cluster"] == "default"
