"""``obs/`` subsystem coverage (ISSUE 3): run-report schema (golden fixture +
version-bump drift), the mode-3 ``--report-json`` smoke, the error-path flush
bugfix, disabled-mode zero-overhead, metrics accumulation under the what-if
fan-out, and the deprecated ``utils/timers.py`` compat shim."""
from __future__ import annotations

import json
import os

import pytest

from kafka_assigner_tpu import obs
from kafka_assigner_tpu.cli import run_tool
from kafka_assigner_tpu.obs import metrics as metrics_mod
from kafka_assigner_tpu.obs import report as report_mod
from kafka_assigner_tpu.obs import trace as trace_mod
from kafka_assigner_tpu.utils.timers import Timers

FIXTURE = os.path.join(
    os.path.dirname(__file__), "golden", "run_report_v1.json"
)

OBS_KNOBS = ("KA_OBS_ENABLE", "KA_OBS_REPORT", "KA_OBS_HIST_EDGES")


@pytest.fixture(autouse=True)
def _hermetic_obs_env(monkeypatch):
    """Every test here starts from the shipped default: obs off, no report
    path, default histogram edges."""
    for knob in OBS_KNOBS:
        monkeypatch.delenv(knob, raising=False)


@pytest.fixture()
def snapshot(tmp_path):
    """6 brokers across 3 racks, one RF-3 topic — small enough that the
    in-process CLI solves stay cheap for tier-1."""
    cluster = {
        "brokers": [
            {"id": 100 + i, "host": f"h{i}", "port": 9092, "rack": f"r{i % 3}"}
            for i in range(6)
        ],
        "topics": {
            "events": {
                str(p): [100 + (p + i) % 5 for i in range(3)] for p in range(4)
            },
        },
    }
    path = tmp_path / "cluster.json"
    path.write_text(json.dumps(cluster))
    return str(path)


# --- run-report schema: golden fixture + version bump -------------------------

def test_golden_fixture_is_schema_valid():
    with open(FIXTURE, "r", encoding="utf-8") as f:
        fixture = json.load(f)
    assert report_mod.validate_report(fixture) == []
    # A schema bump MUST regenerate the checked-in fixture (scripts/lint.sh
    # enforces the same via `obs.report --check-fixture`).
    assert fixture["schema_version"] == report_mod.REPORT_SCHEMA_VERSION


def test_version_drift_fails_validation():
    with open(FIXTURE, "r", encoding="utf-8") as f:
        fixture = json.load(f)
    fixture["schema_version"] = report_mod.REPORT_SCHEMA_VERSION + 1
    problems = report_mod.validate_report(fixture)
    assert any("schema_version" in p for p in problems)


def test_validator_catches_structural_drift():
    with open(FIXTURE, "r", encoding="utf-8") as f:
        fixture = json.load(f)
    del fixture["plan"]
    fixture["status"] = "partial"
    fixture["spans"][0].pop("ms")
    del fixture["metrics"]["histograms"]
    problems = report_mod.validate_report(fixture)
    assert any("missing required key 'plan'" in p for p in problems)
    assert any("status" in p for p in problems)
    assert any("span[0]" in p for p in problems)
    assert any("metrics.histograms" in p for p in problems)
    assert report_mod.validate_report([]) == ["report is not a JSON object"]


def test_fixture_check_cli_entrypoint(tmp_path, capsys):
    assert report_mod.main(["--check-fixture", FIXTURE]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    assert report_mod.main(["--check-fixture", str(bad)]) == 1
    capsys.readouterr()  # drain stderr diagnostics


# --- tier-1 smoke: mode 3 with --report-json ----------------------------------

def test_mode3_report_smoke(snapshot, tmp_path, capsys):
    """The acceptance-criteria smoke: a synthetic PRINT_REASSIGNMENT solve
    with ``--report-json`` emits a schema-versioned report carrying
    encode/solve/decode spans, ZK op counters, and plan stats."""
    report_path = tmp_path / "report.json"
    rc = run_tool([
        "--zk_string", f"file://{snapshot}", "--mode", "PRINT_REASSIGNMENT",
        "--solver", "tpu", "--report-json", str(report_path),
    ])
    capsys.readouterr()
    assert rc == 0
    with open(report_path, "r", encoding="utf-8") as f:
        report = json.load(f)
    assert report_mod.validate_report(report) == []
    assert report["status"] == "ok"
    assert report["mode"] == "PRINT_REASSIGNMENT"
    names = {s["name"] for s in report["spans"]}
    assert {"encode", "solve", "decode"} <= names
    # Phase spans nest under the mode span.
    mode_span = report["spans"][0]
    assert mode_span["path"] == "mode/PRINT_REASSIGNMENT"
    assert all(s["status"] == "ok" for s in report["spans"])
    assert report["metrics"]["counters"]["zk.reads"] >= 1
    assert report["metrics"]["counters"]["zk.bytes"] > 0
    assert "encode.pad_waste_frac" in report["metrics"]["gauges"]
    # The streaming ingest (ISSUE 4) spans/gauges every mode-3 TPU run,
    # snapshot backend included; the zk.pipeline.* counters are live-wire
    # only and asserted in tests/test_zk_socket.py against the jute server.
    paths = {s["path"] for s in report["spans"]}
    assert (
        "mode/PRINT_REASSIGNMENT/metadata/assignment/ingest/stream" in paths
    )
    gauges = report["metrics"]["gauges"]
    assert gauges["ingest.topics"] == 1
    assert gauges["ingest.encode_ms"] >= 0.0
    assert gauges["ingest.overlap_ms"] >= 0.0
    for key in ("moves", "leader_churn", "topics", "partitions"):
        assert key in report["plan"]
    assert report["plan"]["partitions"] == 4


def test_error_path_still_emits_report(snapshot, tmp_path, capsys):
    """The satellite bugfix: a solve raising mid-phase must still flush its
    spans (marked error) and emit the report with ``"status": "error"``."""
    from kafka_assigner_tpu.errors import IngestError

    report_path = tmp_path / "report.json"
    # Phase-tagged since ISSUE 5: a missing topic is an ingest failure (the
    # raw KeyError rides along as __cause__ for library callers).
    with pytest.raises(IngestError, match="no_such_topic") as exc_info:
        run_tool([
            "--zk_string", f"file://{snapshot}", "--mode",
            "PRINT_REASSIGNMENT", "--topics", "no_such_topic",
            "--report-json", str(report_path),
        ])
    assert isinstance(exc_info.value.__cause__, KeyError)
    capsys.readouterr()
    with open(report_path, "r", encoding="utf-8") as f:
        report = json.load(f)
    assert report_mod.validate_report(report) == []
    assert report["status"] == "error"
    assert report["error"]["type"] == "IngestError"
    assert "no_such_topic" in report["error"]["message"]
    # The spans the exception unwound through flushed with error status —
    # timing data survives exactly when it matters most.
    assert report["spans"], "spans lost on the failure path"
    assert any(s["status"] == "error" for s in report["spans"])


# --- disabled mode: zero overhead, byte-identical output ----------------------

def test_disabled_mode_uses_shared_noop_singleton():
    assert obs.active_run() is None
    assert obs.span("anything") is trace_mod.NULL_SPAN
    assert obs.span("other") is trace_mod.NULL_SPAN
    assert metrics_mod.hist_ms("zk.op_ms") is trace_mod.NULL_SPAN
    # Metric writes with no capture are pure no-ops.
    obs.counter_add("zk.reads")
    obs.gauge_set("plan.moves", 1)
    obs.hist_observe("whatif.dispatch_ms", 1.0)
    assert not obs.obs_active()


def test_disabled_run_is_byte_identical_and_fileless(
    snapshot, tmp_path, monkeypatch, capsys
):
    argv = [
        "--zk_string", f"file://{snapshot}", "--mode", "PRINT_REASSIGNMENT",
        "--solver", "tpu",
    ]
    assert run_tool(argv) == 0
    baseline = capsys.readouterr()

    monkeypatch.setenv("KA_OBS_ENABLE", "0")
    assert run_tool(argv) == 0
    disabled = capsys.readouterr()
    # KA_OBS_ENABLE=0 is byte-identical to a build without the subsystem.
    assert disabled.out == baseline.out
    assert disabled.err == baseline.err
    assert "obs:" not in disabled.err
    assert list(tmp_path.glob("*.json")) == [tmp_path / "cluster.json"]

    monkeypatch.setenv("KA_OBS_ENABLE", "1")
    assert run_tool(argv) == 0
    enabled = capsys.readouterr()
    # Collection never perturbs the payload: stdout stays byte-identical;
    # only stderr gains the obs summary (and no file without a path).
    assert enabled.out == baseline.out
    assert "obs: run ok mode=PRINT_REASSIGNMENT" in enabled.err
    assert list(tmp_path.glob("*.json")) == [tmp_path / "cluster.json"]


def test_ka_obs_report_env_default_path(snapshot, tmp_path, monkeypatch, capsys):
    report_path = tmp_path / "envreport.json"
    monkeypatch.setenv("KA_OBS_REPORT", str(report_path))
    assert run_tool([
        "--zk_string", f"file://{snapshot}", "--mode", "PRINT_CURRENT_BROKERS",
    ]) == 0
    capsys.readouterr()
    with open(report_path, "r", encoding="utf-8") as f:
        report = json.load(f)
    assert report_mod.validate_report(report) == []
    assert report["mode"] == "PRINT_CURRENT_BROKERS"


# --- metrics accumulation under the what-if fan-out ---------------------------

def test_whatif_fanout_metrics():
    from kafka_assigner_tpu.parallel.whatif import evaluate_removal_scenarios

    from .test_invariants import make_cluster

    current, live, rack_map = make_cluster(3, 8, 16, 3, 4)
    topics = {"t0": current}
    scenarios = [[], [100], [101]]
    with obs.run_capture() as run:
        results = evaluate_removal_scenarios(
            topics, live, rack_map, scenarios, 3
        )
    assert len(results) == 3
    assert run.counters["whatif.scenarios"] == 3
    # The dispatched fan-out is the padded batch width the device sees.
    assert run.gauges["whatif.fanout"] >= 3
    assert any(s["path"].startswith("whatif/") for s in run.spans)
    # The capture closed: nothing records afterwards.
    assert obs.active_run() is None


# --- span mechanics -----------------------------------------------------------

def test_spans_nest_and_mark_failure():
    with obs.run_capture() as run:
        with pytest.raises(RuntimeError):
            with obs.span("outer"):
                with obs.span("inner"):
                    pass
                with obs.span("boom"):
                    raise RuntimeError("x")
    by_name = {s["name"]: s for s in run.spans}
    assert by_name["inner"]["parent"] == 0
    assert by_name["inner"]["path"] == "outer/inner"
    assert by_name["inner"]["depth"] == 1
    assert by_name["inner"]["status"] == "ok"
    assert by_name["boom"]["status"] == "error"
    assert by_name["outer"]["status"] == "error"


def test_span_cap_overflow_is_counted_not_silent(monkeypatch):
    monkeypatch.setattr(trace_mod, "MAX_SPANS", 2)
    with obs.run_capture() as run:
        for i in range(5):
            with obs.span(f"s{i}"):
                pass
    assert len(run.spans) == 2
    assert run.spans_dropped == 3
    report = report_mod.build_report(run)
    assert report["spans_dropped"] == 3


def test_run_capture_nests_by_save_restore():
    with obs.run_capture() as outer:
        obs.counter_add("zk.reads")
        with obs.run_capture() as inner:
            obs.counter_add("zk.reads", 5)
        assert obs.active_run() is outer
        obs.counter_add("zk.reads")
    assert outer.counters["zk.reads"] == 2
    assert inner.counters["zk.reads"] == 5


def test_histogram_bucketing_and_edges_knob(monkeypatch, capsys):
    monkeypatch.setenv("KA_OBS_HIST_EDGES", "10,1")  # unsorted on purpose
    with obs.run_capture() as run:
        for v in (0.5, 5.0, 50.0):
            obs.hist_observe("zk.op_ms", v)
    h = run.hists["zk.op_ms"]
    assert h["edges"] == [1.0, 10.0]
    assert h["counts"] == [1, 1, 1]  # <=1, <=10, overflow
    assert h["count"] == 3 and h["min"] == 0.5 and h["max"] == 50.0

    monkeypatch.setenv("KA_OBS_HIST_EDGES", "not,numbers")
    assert metrics_mod.resolve_hist_edges() == metrics_mod.DEFAULT_HIST_EDGES
    assert "KA_OBS_HIST_EDGES" in capsys.readouterr().err  # loud ignore

    # nan/inf break bucketing (`value > nan` is always False), duplicates
    # make unreachable phantom buckets, non-positive edges are dead for ms
    # values — all rejected as malformed, loudly.
    for bad in ("nan,5", "5,5,100", "-5,100", "0,10"):
        monkeypatch.setenv("KA_OBS_HIST_EDGES", bad)
        assert (
            metrics_mod.resolve_hist_edges() == metrics_mod.DEFAULT_HIST_EDGES
        ), bad
        assert "KA_OBS_HIST_EDGES" in capsys.readouterr().err


def test_default_hist_edges_doc_matches_constant():
    """The knob registry's default_doc (and therefore the generated README
    knob table) must track obs/metrics.DEFAULT_HIST_EDGES — nothing else
    gates this drift channel."""
    from kafka_assigner_tpu.utils.env import KNOBS

    documented = KNOBS["KA_OBS_HIST_EDGES"].default_doc.strip("`")
    assert documented == ",".join(
        f"{e:g}" for e in metrics_mod.DEFAULT_HIST_EDGES
    )


def test_span_fail_forces_error_status():
    """Failures signaled by return code rather than exception (the CLI's
    nonzero-rc paths) must not leave an ok span in an error report."""
    with obs.run_capture() as run:
        with obs.span("mode/X") as sp:
            sp.fail()
    assert run.spans[0]["status"] == "error"
    # The disabled-mode singleton carries the same interface.
    with obs.span("noop") as sp:
        sp.fail()


def test_span_log_contract_survives_failure():
    """``span(log=...)`` keeps the pre-obs Timers stderr contract: the phase
    line is emitted at INFO on success AND when an exception unwinds, with
    or without an active capture."""
    import logging

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger("test_obs.phase_log")
    logger.setLevel(logging.INFO)
    logger.propagate = False
    handler = _Capture()
    logger.addHandler(handler)
    try:
        with obs.span("encode", log=logger):
            pass
        with pytest.raises(RuntimeError):
            with obs.span("solve", log=logger):
                raise RuntimeError("mid-phase")
    finally:
        logger.removeHandler(handler)
    assert any(m.startswith("phase encode:") for m in records)
    assert any(m.startswith("phase solve:") for m in records)


# --- utils/timers.py compat shim ----------------------------------------------

def test_timers_shim_accumulates_without_capture():
    timers = Timers()
    with timers.phase("encode"):
        pass
    with timers.phase("encode"):
        pass
    assert set(timers.ms) == {"encode"}
    assert timers.ms["encode"] >= 0.0
    assert timers.report() == timers.ms


def test_timers_shim_records_spans_under_capture():
    timers = Timers()
    with obs.run_capture() as run:
        with timers.phase("solve"):
            pass
    assert [s["name"] for s in run.spans] == ["solve"]
    assert "solve" in timers.ms  # the live last_timers contract, obs or not
