"""Shared assignment verifier, ported from the reference test suite's
``verifyPartitionsAndBuildReplicaCounts`` (``KafkaTopicAssignerTest.java:159-187``)
plus the extra invariants SURVEY.md §4 calls for (rack exclusivity, capacity)."""
from __future__ import annotations

import math
from typing import Dict, List, Mapping, Sequence


def verify_and_count(
    current: Mapping[int, Sequence[int]],
    new: Mapping[int, Sequence[int]],
    minimal_movement_threshold: int = 1,
) -> Dict[int, int]:
    """Assert validity + stickiness; return broker -> replica-count histogram."""
    counts: Dict[int, int] = {}
    for partition, replicas in new.items():
        # No broker appears twice in one replica list (KafkaTopicAssignerTest.java:168).
        assert len(replicas) == len(set(replicas)), (
            f"partition {partition} has duplicate brokers: {replicas}"
        )
        for broker in replicas:
            counts[broker] = counts.get(broker, 0) + 1
        # Stickiness: >= threshold survivors from the old set
        # (KafkaTopicAssignerTest.java:179-184).
        overlap = set(replicas) & set(current[partition])
        assert len(overlap) >= minimal_movement_threshold, (
            f"partition {partition} moved entirely: {current[partition]} -> {replicas}"
        )
    return counts


def verify_full_invariants(
    new: Mapping[int, Sequence[int]],
    rack_assignment: Mapping[int, str],
    brokers: Sequence[int],
    replication_factor: int,
) -> None:
    """Extra structural invariants of any valid solve (SURVEY.md §4):
    exact RF, rack exclusivity, per-node capacity ceil(P*RF/N)."""
    cap = math.ceil(len(new) * replication_factor / len(brokers))
    counts: Dict[int, int] = {}
    for partition, replicas in new.items():
        assert len(replicas) == replication_factor, (
            f"partition {partition}: expected RF={replication_factor}, got {replicas}"
        )
        racks = [rack_assignment.get(b, str(b)) for b in replicas]
        assert len(racks) == len(set(racks)), (
            f"partition {partition} has two replicas on one rack: {replicas} -> {racks}"
        )
        for broker in replicas:
            assert broker in set(brokers), f"unknown broker {broker}"
            counts[broker] = counts.get(broker, 0) + 1
    for broker, count in counts.items():
        assert count <= cap, f"broker {broker} over capacity: {count} > {cap}"


def moved_replicas(
    current: Mapping[int, Sequence[int]], new: Mapping[int, Sequence[int]]
) -> int:
    """Number of replicas that changed broker — the BASELINE movement metric."""
    moved = 0
    for partition, replicas in new.items():
        old = set(current.get(partition, ()))
        moved += sum(1 for b in replicas if b not in old)
    return moved


def native_available() -> bool:
    """True when the C++ greedy backend can be built/loaded on this machine."""
    try:
        from kafka_assigner_tpu.solvers.base import get_solver

        get_solver("native")
        return True
    except NotImplementedError:
        return False
