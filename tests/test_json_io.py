"""Reassignment-JSON byte-format and round-trip tests
(contract: ``KafkaAssignmentGenerator.java:169-186`` and Kafka's
``formatAsReassignmentJson``)."""
from __future__ import annotations

import json

from kafka_assigner_tpu.io.base import BrokerInfo
from kafka_assigner_tpu.io.json_io import (
    format_brokers_json,
    format_reassignment_json,
    parse_reassignment_json,
)


def test_reassignment_json_shape_and_compactness():
    payload = format_reassignment_json({"t": {1: [3, 1], 0: [1, 2]}})
    # Kafka's Json.encode byte order (scala Map insertion order): version
    # first, topic/partition/replicas; compact, partitions ascending, replica
    # order preserved (leadership order!).
    assert payload == (
        '{"version":1,"partitions":['
        '{"topic":"t","partition":0,"replicas":[1,2]},'
        '{"topic":"t","partition":1,"replicas":[3,1]}]}'
    )


def test_new_assignment_pairs_orgjson_byte_order():
    # org.json on JDK8 walks HashMap bucket order: partitions before version,
    # and partition/replicas/topic within an entry (json_io module docstring).
    from kafka_assigner_tpu.io.json_io import format_reassignment_pairs

    payload = format_reassignment_pairs([("t", {1: [3, 1], 0: [1, 2]})])
    assert payload == (
        '{"partitions":['
        '{"partition":0,"replicas":[1,2],"topic":"t"},'
        '{"partition":1,"replicas":[3,1],"topic":"t"}],"version":1}'
    )


def test_reassignment_topic_order_follows_cli_order():
    payload = format_reassignment_json(
        {"b": {0: [1]}, "a": {0: [2]}}, topic_order=["b", "a"]
    )
    parts = json.loads(payload)["partitions"]
    assert [e["topic"] for e in parts] == ["b", "a"]


def test_reassignment_round_trip():
    original = {"events": {0: [1, 2, 3], 1: [2, 3, 4]}, "logs": {0: [5, 6, 7]}}
    assert parse_reassignment_json(format_reassignment_json(original)) == original


def test_parse_rejects_bad_version():
    import pytest

    with pytest.raises(ValueError, match="version"):
        parse_reassignment_json('{"version":2,"partitions":[]}')


def test_brokers_json_rack_optional():
    # rack key present iff defined (KafkaAssignmentGenerator.java:122-124);
    # key order is org.json-on-JDK8 bucket order.
    payload = format_brokers_json(
        [BrokerInfo(1, "h1", 9092, "r1"), BrokerInfo(2, "h2", 9092, None)]
    )
    assert payload == (
        '[{"rack":"r1","port":9092,"host":"h1","id":1},'
        '{"port":9092,"host":"h2","id":2}]'
    )


def test_non_ascii_passes_through_raw():
    # org.json (the reference's serializer) writes non-ASCII raw, not \uXXXX
    # escaped; Kafka restricts topic names to ASCII, but host names and any
    # future fields must round-trip identically.
    payload = format_reassignment_json({"tøpic": {0: [1]}})
    assert "tøpic" in payload and "\\u" not in payload
    assert parse_reassignment_json(payload) == {"tøpic": {0: [1]}}
