"""The full randomized chaos soak (ISSUE 5 acceptance): 200 seed-
deterministic fault schedules through the whole mode-3 pipeline against the
in-repo jute server — zero hangs, and every run either byte-identical to
the no-fault baseline or exiting with the documented degraded/failure code
and a self-accounting run report.

Slow-marked: the fast one-fault-per-class matrix runs in tier-1 via
``scripts/lint.sh`` (``chaos_soak.py --matrix``); this is the long tail.
"""
from __future__ import annotations

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SOAK = os.path.join(REPO, "scripts", "chaos_soak.py")


@pytest.mark.slow
def test_chaos_soak_200_schedules():
    # A subprocess (not in-process) so the soak's env mutation and fault
    # schedules cannot leak into the suite, and so a hang is bounded by the
    # outer timeout rather than wedging the pytest worker.
    proc = subprocess.run(
        [sys.executable, SOAK, "--runs", "200", "--solver", "tpu"],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        text=True,
        timeout=1800,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "chaos_soak: PASS" in proc.stderr
