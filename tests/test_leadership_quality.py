"""Leadership-balance quality at scale: the reference's stated purpose for the
preference ordering is that "each node is the leader of roughly the same
number of partitions" (``KafkaAssignmentStrategy.java:216-218``). The
scenario tests never measure it; these do, for every backend, across a
multi-topic cluster solved through one shared Context."""
from __future__ import annotations

from collections import Counter

import pytest

from kafka_assigner_tpu.assigner import TopicAssigner

from .test_invariants import make_cluster
from .test_strategy_scenarios import SOLVERS


@pytest.mark.parametrize("solver", SOLVERS)
def test_leader_balance_across_topics(solver):
    current, live, rack_map = make_cluster(0, 20, 40, 3, 4)
    assigner = TopicAssigner(solver)
    leaders: Counter = Counter()
    slot_counts = [Counter() for _ in range(3)]
    for t in range(8):
        out = assigner.generate_assignment(f"topic-{t:02d}", current, live, rack_map, -1)
        for replicas in out.values():
            leaders[replicas[0]] += 1
            for slot, b in enumerate(replicas):
                slot_counts[slot][b] += 1

    total = 8 * 40
    ideal = total / len(live)
    # Every broker leads, and no broker leads more than ~2x its fair share.
    assert set(leaders) == set(live)
    assert max(leaders.values()) <= 2 * ideal, dict(leaders)
    assert min(leaders.values()) >= ideal / 2, dict(leaders)
    # Fallback (slot-1) coverage balances too (the reference weights fallback
    # leaders explicitly, KafkaAssignmentStrategy.java:254-257).
    assert max(slot_counts[1].values()) <= 2 * ideal


@pytest.mark.parametrize("solver", SOLVERS)
def test_leader_spread_tight_on_uniform_sets(solver):
    # Identical replica sets across many partitions: leadership must rotate
    # (perfect balance up to integer rounding), not stick to one broker.
    current = {p: [10, 11, 12] for p in range(30)}
    out = TopicAssigner(solver).generate_assignment(
        "uniform", current, {10, 11, 12}, {}, -1
    )
    leaders = Counter(r[0] for r in out.values())
    assert sorted(leaders.values()) == [10, 10, 10]
